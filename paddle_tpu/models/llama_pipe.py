"""Pipeline-parallel Llama (ecosystem parity: PaddleNLP
paddlenlp/transformers/llama/modeling_pp.py LlamaForCausalLMPipe).

The monolithic LlamaForCausalLM decomposes into single-tensor pipeline
stages for fleet's PipelineLayer engine (scanned shard_map + ppermute
over the 'stage' axis, meta_parallel/pipeline_parallel.py): embedding ->
N decoder layers -> final-norm + lm_head. Each decoder stage owns its
rope trig table (a derived constant — duplicating it per stage costs a
few KB and keeps stage inputs to ONE activation tensor, which is what
the p2p handoff wants on TPU)."""
from __future__ import annotations

from ..nn.layer_base import Layer
from ..nn.layers_common import Embedding, Linear
from ..nn.initializer import Normal
from ..tensor import Tensor
from .llama import (LlamaConfig, LlamaDecoderLayer, LlamaRMSNorm,
                    LlamaPretrainingCriterion, rope_freqs)

__all__ = ["LlamaForCausalLMPipe"]


# one trig table per (head_dim, max_pos, theta) — for the 7B config
# cos+sin is ~4 MB, so per-layer copies would waste ~L*4 MB
_ROPE_CACHE = {}


class _RopeMixin:
    def _attach_rope(self, config):
        # plain constants, NOT buffers: the pipeline engine requires
        # buffer-free stage bodies (PipelineTrainStep threads only
        # params through the scanned stages); the shared table gets
        # constant-folded into each stage's XLA program
        key = (config.hidden_size // config.num_attention_heads,
               config.max_position_embeddings, config.rope_theta)
        if key not in _ROPE_CACHE:
            _ROPE_CACHE[key] = rope_freqs(*key)
        self._rope_cos, self._rope_sin = _ROPE_CACHE[key]

    def _rope_slice(self, s):
        return Tensor(self._rope_cos[:s]), Tensor(self._rope_sin[:s])


class LlamaEmbeddingPipe(Layer):
    """Stage 0: token embedding. input_ids -> hidden."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = Normal(0.0, config.initializer_range)
        if config.tensor_parallel:
            from ..distributed.fleet.meta_parallel.mp_layers import (
                VocabParallelEmbedding)
            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.embed_tokens = Embedding(config.vocab_size,
                                          config.hidden_size,
                                          weight_attr=init)

    def forward(self, input_ids):
        return self.embed_tokens(input_ids)


class LlamaDecoderLayerPipe(Layer, _RopeMixin):
    """One decoder block as a single-tensor stage (causal, no cache —
    the pipeline engine is the training path)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.layer = LlamaDecoderLayer(config)
        self._attach_rope(config)

    def forward(self, hidden_states):
        s = hidden_states.shape[1]
        cos, sin = self._rope_slice(s)
        out, _ = self.layer(hidden_states, cos, sin, None, None, None)
        return out


class LlamaHeadPipe(Layer):
    """Last stage: final RMSNorm + LM head. hidden -> logits.

    tie_word_embeddings: holds the stage-0 embedding layer itself (one
    shared Parameter object). Both embedding and head run UNSTAGED (pre/
    postamble of PipelineTrainStep), and the train step rebinds the
    pre-side traced value into the postamble — one gradient, one update
    (the SharedLayerDesc role of the reference's modeling_pp.py)."""

    def __init__(self, config: LlamaConfig, embedding=None):
        super().__init__()
        self.norm = LlamaRMSNorm(config)
        init = Normal(0.0, config.initializer_range)
        if config.tie_word_embeddings:
            if embedding is None:
                raise ValueError(
                    "tie_word_embeddings head needs the embedding stage")
            self.lm_head = None
            self.tied_embed = embedding
        elif config.tensor_parallel:
            from ..distributed.fleet.meta_parallel.mp_layers import (
                ColumnParallelLinear)
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, weight_attr=init,
                has_bias=False, gather_output=False)
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=init, bias_attr=False)

    def forward(self, hidden_states):
        h = self.norm(hidden_states)
        if self.lm_head is None:
            from .llama import parallel_matmul
            return parallel_matmul(h, self.tied_embed.embed_tokens.weight,
                                   transpose_y=True)
        return self.lm_head(h)


def LlamaForCausalLMPipe(config: LlamaConfig, num_stages=None,
                         num_virtual_pipeline_stages=None,
                         recompute_interval=0, seg_method="uniform"):
    """Build the PipelineLayer for Llama causal-LM pretraining.

    Use with fleet (pp_degree > 1):
        model = fleet.distributed_model(LlamaForCausalLMPipe(cfg))
        loss = model.train_batch([ids, labels], optimizer=opt)
    (the embedded LlamaPretrainingCriterion is the default loss_fn;
    pass loss_fn= to override.)

    The effective stage count comes from the bound mesh's 'stage' axis
    (fleet pp_degree); num_stages here must match it when a mesh is
    already initialized.
    """
    from ..distributed.fleet.meta_parallel import PipelineLayer
    from ..distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is not None and num_stages is not None:
        pp = int(mesh.shape.get("stage", 1))
        if pp != num_stages:
            raise ValueError(
                f"num_stages={num_stages} but the bound mesh has "
                f"stage degree {pp} (fleet pp_degree) — the mesh wins; "
                "drop num_stages or make them agree")
    embed = LlamaEmbeddingPipe(config)
    stages = ([embed]
              + [LlamaDecoderLayerPipe(config)
                 for _ in range(config.num_hidden_layers)]
              + [LlamaHeadPipe(config, embedding=embed
                               if config.tie_word_embeddings else None)])
    return PipelineLayer(
        stages, num_stages=num_stages,
        num_virtual_pipeline_stages=num_virtual_pipeline_stages,
        recompute_interval=recompute_interval, seg_method=seg_method,
        loss_fn=LlamaPretrainingCriterion(config))
