"""Shared plumbing for HuggingFace checkpoint importers."""
from __future__ import annotations

import numpy as np


def hf_tensor_to_numpy(p):
    """torch tensors may be CUDA-resident or bf16 — both reject
    .numpy(); plain arrays pass through."""
    if hasattr(p, "detach"):
        p = p.detach().cpu()
        if str(p.dtype) == "torch.bfloat16":
            p = p.float()
        return p.numpy()
    return np.asarray(p)


def validate_keys(model, sd, what):
    own = set(model.state_dict())
    unknown = [k for k in sd if k not in own]
    missing = [k for k in own if k not in sd]
    if unknown or missing:
        raise ValueError(f"{what} state_dict mismatch: "
                         f"unknown={unknown[:5]} missing={missing[:5]}")


ENCODER_KEY_MAP = [
    ("encoder.layer.", "encoder.layers."),
    (".attention.self.query", ".self_attn.q_proj"),
    (".attention.self.key", ".self_attn.k_proj"),
    (".attention.self.value", ".self_attn.v_proj"),
    (".attention.output.dense", ".self_attn.out_proj"),
    (".attention.output.LayerNorm", ".norm1"),
    (".intermediate.dense", ".linear1"),
    (".output.dense", ".linear2"),
    (".output.LayerNorm", ".norm2"),
]


def load_hf_encoder_state(model, hf_state_dict, key_fn, what,
                          skip=lambda n: False,
                          backfill_prefixes=()):
    """Shared BERT-style encoder import: skip position_ids buffers and
    caller-specified keys, rename via key_fn (ENCODER_KEY_MAP + model
    specifics), transpose 2-D non-embedding Linear weights to paddle's
    [in, out], backfill model-owned params HF checkpoints omit (e.g.
    the pooler when HF built the head with add_pooling_layer=False),
    validate and load."""
    from ..tensor import Tensor
    sd = {}
    for name, p in hf_state_dict.items():
        if name.endswith("position_ids") or skip(name):
            continue
        n = key_fn(name)
        a = hf_tensor_to_numpy(p)
        if n.endswith(".weight") and a.ndim == 2 and "embeddings" not in n:
            a = a.T
        sd[n] = Tensor(np.ascontiguousarray(a))
    own = model.state_dict()
    for k in own:
        if any(k.startswith(pfx) for pfx in backfill_prefixes) \
                and k not in sd:
            sd[k] = own[k]
    validate_keys(model, sd, what)
    model.set_state_dict(sd)
    return model
