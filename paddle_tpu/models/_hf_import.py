"""Shared plumbing for HuggingFace checkpoint importers."""
from __future__ import annotations

import numpy as np


def hf_tensor_to_numpy(p):
    """torch tensors may be CUDA-resident or bf16 — both reject
    .numpy(); plain arrays pass through."""
    if hasattr(p, "detach"):
        p = p.detach().cpu()
        if str(p.dtype) == "torch.bfloat16":
            p = p.float()
        return p.numpy()
    return np.asarray(p)


def validate_keys(model, sd, what):
    own = set(model.state_dict())
    unknown = [k for k in sd if k not in own]
    missing = [k for k in own if k not in sd]
    if unknown or missing:
        raise ValueError(f"{what} state_dict mismatch: "
                         f"unknown={unknown[:5]} missing={missing[:5]}")
