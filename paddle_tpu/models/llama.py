"""Llama family — the flagship pretraining model (driver config #3 /
north star: Llama-2-7B via Fleet sharding-3 + TP at ≥40% MFU).

Ecosystem parity: PaddleNLP paddlenlp/transformers/llama/modeling.py
(LlamaAttention/LlamaMLP/LlamaRMSNorm/LlamaForCausalLM with
fused_rotary_position_embedding + RingFlashAttention recipes).

TPU-native design:
- attention in [B, S, H, D] flash layout feeding the Pallas flash kernel
  (kernels/attention.py); GQA via K/V head broadcast inside the kernel
  wrapper;
- RoPE from kernels/rope.py (XLA-fused elementwise);
- RMSNorm via the fused kernel; SwiGLU MLP;
- TP through fleet's Column/Row/VocabParallel layers (GSPMD specs) so the
  same module runs single-chip or under any mesh;
- sequence dim ready for 'context' sharding (ring attention) — activations
  keep seq on axis 1 throughout.
"""
from __future__ import annotations

import math as pymath
from dataclasses import dataclass, field

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers_common import Embedding, Linear, LayerList, Dropout
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import manipulation as M
from ..ops._dispatch import apply
from ..ops.creation import _coerce
from ..kernels.rope import rope_freqs, apply_rotary_emb
from ..kernels.norm import fused_rms_norm
from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    parallel_matmul, mark_partition)
from ..distributed.fleet.recompute import recompute
from ..generation import GenerationMixin
from ..generation.kv_cache import (StaticCacheEntry, StaticKVCache,
                                   PagedKVCache)


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_recompute: bool = False
    tensor_parallel: bool = True
    dtype: str = "float32"

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=4, max_position_embeddings=256)
        base.update(kw)
        return LlamaConfig(**base)


class LlamaRMSNorm(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        from ..nn.initializer import Constant
        self.weight = self.create_parameter(
            [config.hidden_size], default_initializer=Constant(1.0))
        self.variance_epsilon = config.rms_norm_eps

    def forward(self, x):
        return apply(lambda v, w: fused_rms_norm(v, w, self.variance_epsilon),
                     x, self.weight, _name="rms_norm")


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        init = Normal(0.0, config.initializer_range)
        LinQ = ColumnParallelLinear if config.tensor_parallel else Linear
        LinO = RowParallelLinear if config.tensor_parallel else Linear
        kw = dict(gather_output=False) if config.tensor_parallel else {}
        okw = dict(input_is_parallel=True) if config.tensor_parallel else {}
        self.q_proj = LinQ(self.hidden_size, self.num_heads * self.head_dim,
                           weight_attr=init, has_bias=False, **kw) \
            if config.tensor_parallel else Linear(
                self.hidden_size, self.num_heads * self.head_dim,
                weight_attr=init, bias_attr=False)
        self.k_proj = LinQ(self.hidden_size, self.num_kv_heads * self.head_dim,
                           weight_attr=init, has_bias=False, **kw) \
            if config.tensor_parallel else Linear(
                self.hidden_size, self.num_kv_heads * self.head_dim,
                weight_attr=init, bias_attr=False)
        self.v_proj = LinQ(self.hidden_size, self.num_kv_heads * self.head_dim,
                           weight_attr=init, has_bias=False, **kw) \
            if config.tensor_parallel else Linear(
                self.hidden_size, self.num_kv_heads * self.head_dim,
                weight_attr=init, bias_attr=False)
        self.o_proj = LinO(self.num_heads * self.head_dim, self.hidden_size,
                           weight_attr=init, has_bias=False, **okw) \
            if config.tensor_parallel else Linear(
                self.num_heads * self.head_dim, self.hidden_size,
                weight_attr=init, bias_attr=False)

    def forward(self, hidden_states, cos, sin, attn_mask=None,
                position_ids=None, past_key_value=None):
        b, s, _ = hidden_states.shape
        q = M.reshape(self.q_proj(hidden_states),
                      [b, s, self.num_heads, self.head_dim])
        k = M.reshape(self.k_proj(hidden_states),
                      [b, s, self.num_kv_heads, self.head_dim])
        v = M.reshape(self.v_proj(hidden_states),
                      [b, s, self.num_kv_heads, self.head_dim])

        def rope_fn(qv, kv, cv, sv):
            return apply_rotary_emb(qv, kv, cv, sv)
        q, k = apply(rope_fn, q, k, cos, sin, _name="fused_rope")

        from ..generation.kv_cache import PagedCacheEntry
        if isinstance(past_key_value, PagedCacheEntry):
            # paged decode cache (serving continuous batching): write the
            # step's K/V into each slot's page and attend via the paged
            # Pallas kernel — shared contract,
            # generation/kv_cache.py paged_cache_update_attend
            from ..generation.kv_cache import paged_cache_update_attend
            out, new_cache = paged_cache_update_attend(
                past_key_value, q, k, v)
            out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(out), new_cache
        if isinstance(past_key_value, StaticCacheEntry):
            # static-shape decode cache: in-place write at `pos` (shared
            # contract — generation/kv_cache.py static_cache_update)
            from ..generation.kv_cache import static_cache_update
            k, v, new_cache = static_cache_update(past_key_value, k, v)
        elif past_key_value is not None:
            k = M.concat([past_key_value[0], k], axis=1)
            v = M.concat([past_key_value[1], v], axis=1)
            new_cache = (k, v)
        else:
            new_cache = (k, v)

        # GQA: kv heads are NOT repeated here — the flash kernel consumes
        # grouped kv natively (kernels/attention.py GQA index maps) and the
        # XLA fallback repeats internally only when it must.
        causal = past_key_value is None
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=causal,
            training=self.training)
        out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(out), new_cache


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = Normal(0.0, config.initializer_range)
        tp = config.tensor_parallel
        if tp:
            self.gate_proj = ColumnParallelLinear(
                config.hidden_size, config.intermediate_size,
                weight_attr=init, has_bias=False, gather_output=False)
            self.up_proj = ColumnParallelLinear(
                config.hidden_size, config.intermediate_size,
                weight_attr=init, has_bias=False, gather_output=False)
            self.down_proj = RowParallelLinear(
                config.intermediate_size, config.hidden_size,
                weight_attr=init, has_bias=False, input_is_parallel=True)
        else:
            self.gate_proj = Linear(config.hidden_size,
                                    config.intermediate_size,
                                    weight_attr=init, bias_attr=False)
            self.up_proj = Linear(config.hidden_size,
                                  config.intermediate_size,
                                  weight_attr=init, bias_attr=False)
            self.down_proj = Linear(config.intermediate_size,
                                    config.hidden_size,
                                    weight_attr=init, bias_attr=False)

    def forward(self, x):
        from ..incubate.nn.functional import swiglu
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden_states, cos, sin, attn_mask=None,
                position_ids=None, past_key_value=None):
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        h, cache = self.self_attn(h, cos, sin, attn_mask, position_ids,
                                  past_key_value)
        h = residual + h
        residual = h
        h2 = self.post_attention_layernorm(h)
        h2 = self.mlp(h2)
        return residual + h2, cache


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = Normal(0.0, config.initializer_range)
        if config.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.embed_tokens = Embedding(config.vocab_size,
                                          config.hidden_size,
                                          weight_attr=init)
        self.layers = LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config)
        cos, sin = rope_freqs(config.hidden_size // config.num_attention_heads,
                              config.max_position_embeddings,
                              config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                past_key_values=None, use_cache=False):
        h = self.embed_tokens(input_ids)
        s = input_ids.shape[1]
        static_cache = isinstance(past_key_values,
                                  (StaticKVCache, PagedKVCache))
        if position_ids is not None:
            # per-row positions (left-padded generation): gather trig rows
            cos = apply(lambda c, p: jnp.take(c, p, axis=0),
                        self.rope_cos, position_ids, _name="rope_gather")
            sin = apply(lambda c, p: jnp.take(c, p, axis=0),
                        self.rope_sin, position_ids, _name="rope_gather")
        else:
            past_len = 0
            if (not static_cache and past_key_values is not None
                    and past_key_values[0] is not None):
                past_len = past_key_values[0][0].shape[1]
            cos = self.rope_cos[past_len:past_len + s]
            sin = self.rope_sin[past_len:past_len + s]
        caches = []
        for i, layer in enumerate(self.layers):
            pkv = past_key_values[i] if past_key_values is not None else None
            if self.config.use_recompute and self.training and pkv is None:
                h, cache = recompute(layer.forward, h, cos, sin, attn_mask,
                                     position_ids, None)
            else:
                h, cache = layer(h, cos, sin, attn_mask, position_ids, pkv)
            caches.append(cache)
        h = self.norm(h)
        if use_cache:
            return h, caches
        return h


class LlamaForCausalLM(Layer, GenerationMixin):
    supports_static_cache = True

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        init = Normal(0.0, config.initializer_range)
        if config.tie_word_embeddings:
            self.lm_head = None
        elif config.tensor_parallel:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, weight_attr=init,
                has_bias=False, gather_output=False)
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=init, bias_attr=False)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                past_key_values=None, use_cache=False):
        out = self.llama(input_ids, attn_mask, position_ids, past_key_values,
                         use_cache)
        if use_cache:
            h, caches = out
        else:
            h = out
        if self.lm_head is None:
            logits = parallel_matmul(h, self.llama.embed_tokens.weight,
                                     transpose_y=True)
        else:
            logits = self.lm_head(h)
        if use_cache:
            return logits, caches
        return logits

    @property
    def backbone(self):
        return self.llama

    def load_hf_state_dict(self, hf_state_dict):
        """Import HuggingFace Llama weights (ecosystem parity:
        PaddleNLP's convert from transformers checkpoints). Accepts an
        HF model's state_dict (torch tensors or arrays); names map 1:1
        with the `model.` → `llama.` prefix swap and 2-D Linear weights
        transpose to paddle's [in, out] layout. Verified bit-tight
        against transformers (tests/test_hf_parity.py)."""
        from ..tensor import Tensor
        from ._hf_import import hf_tensor_to_numpy as to_np, validate_keys
        import numpy as np
        sd = {}
        for name, p in hf_state_dict.items():
            if name == "lm_head.weight" and self.lm_head is None:
                # tied-embedding checkpoints carry the tied weight under
                # both keys; the tied model reads embed_tokens only
                continue
            a = to_np(p)
            our = name.replace("model.", "llama.", 1)
            if name.endswith(".weight") and a.ndim == 2 \
                    and "embed_tokens" not in name:
                a = a.T
            sd[our] = Tensor(np.ascontiguousarray(a))
        validate_keys(self, sd, "HF Llama")
        self.set_state_dict(sd)
        return self


class LlamaPretrainingCriterion(Layer):
    """Shift-labels causal LM loss (ecosystem parity: PaddleNLP
    LlamaPretrainingCriterion)."""

    def __init__(self, config: LlamaConfig = None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        # logits [B, S, V]; labels [B, S] — predict token t+1
        lg = logits[:, :-1, :]
        lb = labels[:, 1:]
        b, s, v = lg.shape
        loss = F.cross_entropy(M.reshape(lg, [b * s, v]),
                               M.reshape(lb, [b * s]),
                               ignore_index=self.ignore_index)
        return loss
