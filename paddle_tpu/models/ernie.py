"""ERNIE family (driver config #2: "BERT-base / ERNIE-3.0 fine-tune").

Ecosystem parity: PaddleNLP paddlenlp/transformers/ernie/modeling.py —
ERNIE shares BERT's encoder skeleton with task-type embeddings added
(ErnieModel adds `task_type_ids` on top of word/position/token-type)
and PaddleNLP-style task heads (sequence classification, token
classification, question answering).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..nn.layer_base import Layer
from ..nn.layers_common import Embedding, Linear, LayerNorm, Dropout
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import creation as C
from ..ops import manipulation as M

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForTokenClassification", "ErnieForQuestionAnswering"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=128)
        base.update(kw)
        return ErnieConfig(**base)


class ErnieEmbeddings(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        init = Normal(0.0, config.initializer_range)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=init)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=init)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=init)
        self.use_task_id = config.use_task_id
        if config.use_task_id:
            self.task_type_embeddings = Embedding(
                config.task_type_vocab_size, config.hidden_size,
                weight_attr=init)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = C.arange(s, dtype="int64")
        emb = self.word_embeddings(input_ids) \
            + self.position_embeddings(position_ids)
        if token_type_ids is None:
            token_type_ids = C.zeros([s], dtype="int64")
        emb = emb + self.token_type_embeddings(token_type_ids)
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = C.zeros([s], dtype="int64")
            emb = emb + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(emb))


class ErnieModel(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation="gelu")
        self.encoder = TransformerEncoder(layer, config.num_hidden_layers)
        self.pooler = Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        if attention_mask is not None:
            # [B, S] 1/0 -> broadcastable BOOLEAN key mask [B, 1, 1, S]
            # (int masks would be treated as additive bias by SDPA)
            attention_mask = M.reshape(
                attention_mask,
                [attention_mask.shape[0], 1, 1, attention_mask.shape[1]])
            if "bool" not in str(attention_mask.dtype):
                attention_mask = attention_mask.astype("bool")
        h = self.encoder(h, src_mask=attention_mask)
        pooled = F.tanh(self.pooler(h[:, 0]))
        return h, pooled


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask, task_type_ids)
        return self.classifier(self.dropout(pooled))


class ErnieForTokenClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h, _ = self.ernie(input_ids, token_type_ids, position_ids,
                          attention_mask, task_type_ids)
        return self.classifier(self.dropout(h))


class ErnieForQuestionAnswering(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.classifier = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h, _ = self.ernie(input_ids, token_type_ids, position_ids,
                          attention_mask, task_type_ids)
        logits = self.classifier(h)
        start, end = M.split(logits, 2, axis=-1)
        return M.squeeze(start, axis=-1), M.squeeze(end, axis=-1)


def _ernie_hf_key(n):
    """HF Ernie key → our key (shared BERT-style encoder map plus
    Ernie specifics: our pooler is a bare Linear where HF nests dense;
    the QA head is `classifier` where HF uses `qa_outputs`)."""
    from ._hf_import import ENCODER_KEY_MAP
    n = n.replace("ernie.embeddings.LayerNorm", "ernie.embeddings.layer_norm")
    n = n.replace("ernie.pooler.dense.", "ernie.pooler.")
    n = n.replace("qa_outputs.", "classifier.")
    for a, b in ENCODER_KEY_MAP:
        n = n.replace(a, b)
    return n


def _load_hf_ernie(self, hf_state_dict):
    """Import HuggingFace Ernie weights (logits verified ~1e-5 in
    tests/test_hf_parity.py). Token-classification / QA checkpoints
    are built with add_pooling_layer=False upstream — our model's own
    pooler init is kept in that case (those heads never read it)."""
    from ._hf_import import load_hf_encoder_state
    return load_hf_encoder_state(
        self, hf_state_dict, _ernie_hf_key, "HF Ernie",
        backfill_prefixes=("ernie.pooler.",))


ErnieForSequenceClassification.load_hf_state_dict = _load_hf_ernie
ErnieForTokenClassification.load_hf_state_dict = _load_hf_ernie
ErnieForQuestionAnswering.load_hf_state_dict = _load_hf_ernie
