"""BERT/ERNIE family (driver config #2: BERT-base / ERNIE-3.0 fine-tune
with Fleet DP). Ecosystem parity: paddlenlp/transformers/bert/modeling.py."""
from __future__ import annotations

from dataclasses import dataclass

from ..tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers_common import Embedding, Linear, LayerNorm, Dropout, LayerList
from ..nn.transformer import TransformerEncoderLayer, TransformerEncoder
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import linalg as L
from ..ops import manipulation as M
from ..ops import creation as C


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    num_labels: int = 2

    @staticmethod
    def base(**kw):
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=1000, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=128)
        base.update(kw)
        return BertConfig(**base)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = Normal(0.0, config.initializer_range)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=init)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=init)
        self.layer_norm = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = C.arange(s, dtype="int64")
        if token_type_ids is None:
            token_type_ids = C.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden_states):
        first = hidden_states[:, 0]
        return F.tanh(self.dense(first))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            layer_norm_eps=config.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] padding mask → additive [B, 1, 1, S]
            am = M.unsqueeze(attention_mask, [1, 2])
            attention_mask = (1.0 - am.astype("float32")) * -1e4
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        h = self.encoder(h, attention_mask)
        pooled = self.pooler(h)
        return h, pooled


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForTokenClassification(Layer):
    """Parity: paddlenlp BertForTokenClassification."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        h, _ = self.bert(input_ids, token_type_ids, position_ids,
                         attention_mask)
        return self.classifier(self.dropout(h))


class BertForQuestionAnswering(Layer):
    """Parity: paddlenlp BertForQuestionAnswering (SQuAD-style start/end
    span logits)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.classifier = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        h, _ = self.bert(input_ids, token_type_ids, position_ids,
                         attention_mask)
        logits = self.classifier(h)
        start, end = M.unbind(logits, axis=-1)
        return start, end


class BertLMPredictionHead(Layer):
    """Transform + tied-embedding decoder (parity: paddlenlp
    BertLMPredictionHead)."""

    def __init__(self, config: BertConfig, embedding_weights):
        super().__init__()
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.norm = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_eps)
        # tied weight: keep a plain reference (list sidesteps Layer's
        # parameter registration) — the embedding owns the parameter
        self._tied = [embedding_weights]
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True)
        self.act = config.hidden_act

    def forward(self, h):
        h = self.norm(getattr(F, self.act)(self.transform(h)))
        return L.matmul(h, self._tied[0],
                        transpose_y=True) + self.decoder_bias


class BertForMaskedLM(Layer):
    """Parity: paddlenlp BertForMaskedLM."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMPredictionHead(
            config, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        h, _ = self.bert(input_ids, token_type_ids, position_ids,
                         attention_mask)
        return self.cls(h)


class BertForPretraining(Layer):
    """MLM + next-sentence-prediction heads (parity: paddlenlp
    BertForPretraining)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMPredictionHead(
            config, self.bert.embeddings.word_embeddings.weight)
        self.nsp = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        h, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.cls(h), self.nsp(pooled)


# ERNIE shares the architecture (ecosystem parity: ernie models are
# BERT-arch with different pretraining); alias the classes
ErnieConfig = BertConfig
ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification


def _bert_hf_key(n):
    """HF BERT key → our key (shared encoder map + MLM head renames;
    the QA head is `classifier` where HF uses `qa_outputs`)."""
    from ._hf_import import ENCODER_KEY_MAP
    n = n.replace("bert.embeddings.LayerNorm", "bert.embeddings.layer_norm")
    n = n.replace("qa_outputs.", "classifier.")
    for a, b in ENCODER_KEY_MAP:
        n = n.replace(a, b)
    n = n.replace("cls.predictions.transform.dense", "cls.transform")
    n = n.replace("cls.predictions.transform.LayerNorm", "cls.norm")
    return n


def _load_hf_bert(self, hf_state_dict):
    """Import HuggingFace BERT weights (logits verified ~1e-5 in
    tests/test_hf_parity.py). The MLM decoder weight is tied to the
    word embeddings (skipped); its bias maps to cls.decoder_bias. HF
    MaskedLM checkpoints carry no pooler — ours keeps its initialized
    pooler in that case (the MLM head never reads it)."""
    import numpy as np
    from ._hf_import import hf_tensor_to_numpy, load_hf_encoder_state
    if "cls.predictions.decoder.weight" in hf_state_dict:
        # our MLM head is always tied to the word embeddings: an
        # untied/diverged decoder cannot be represented — verify
        # instead of silently mis-importing
        dec = hf_tensor_to_numpy(
            hf_state_dict["cls.predictions.decoder.weight"])
        emb = hf_tensor_to_numpy(
            hf_state_dict["bert.embeddings.word_embeddings.weight"])
        if not np.allclose(dec, emb, atol=1e-6):
            raise ValueError(
                "HF BERT checkpoint has an UNTIED mlm decoder weight; "
                "this model ties the decoder to the word embeddings "
                "and cannot represent it")
    renamed = {("cls.decoder_bias" if k == "cls.predictions.bias"
                else k): v for k, v in hf_state_dict.items()}
    return load_hf_encoder_state(
        self, renamed, _bert_hf_key, "HF BERT",
        skip=lambda n: n.startswith("cls.predictions.decoder."),
        backfill_prefixes=("bert.pooler.",))


BertForMaskedLM.load_hf_state_dict = _load_hf_bert
BertForSequenceClassification.load_hf_state_dict = _load_hf_bert
BertForTokenClassification.load_hf_state_dict = _load_hf_bert
BertForQuestionAnswering.load_hf_state_dict = _load_hf_bert
