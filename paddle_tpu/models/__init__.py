"""Model zoo (ecosystem parity: PaddleNLP model families re-designed
TPU-first; SURVEY.md notes the driver configs require Llama/ERNIE-BERT/
ResNet/SD-UNet capabilities even though their code lives outside the
reference core repo)."""
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM, LlamaPretrainingCriterion
from .llama_pipe import LlamaForCausalLMPipe
from .bert import (BertConfig, BertModel, BertForSequenceClassification,
                   BertForTokenClassification, BertForQuestionAnswering,
                   BertForMaskedLM, BertForPretraining)
from .gpt import GPTConfig, GPTModel, GPTForCausalLM
from .ernie import (ErnieConfig, ErnieModel, ErnieForSequenceClassification,
                    ErnieForTokenClassification, ErnieForQuestionAnswering)
