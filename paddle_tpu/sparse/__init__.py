"""paddle.sparse parity — COO/CSR sparse tensors.

Reference parity: python/paddle/sparse/ (creation, unary/binary ops,
matmul) over phi::SparseCooTensor / SparseCsrTensor
(paddle/phi/core/sparse_coo_tensor.h).

TPU-native design: backed by jax.experimental.sparse.BCOO — the XLA
sparse representation whose ops compile to gather/scatter/segment-sum
HLOs (there is no TPU sparse ALU; this is also how the reference's CPU
fallback works conceptually). CSR creation converts to COO internally;
`to_dense` materializes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..tensor import Tensor
from ..ops.creation import _coerce

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "is_sparse", "is_sparse_coo", "is_sparse_csr",
    "add", "subtract", "multiply", "divide", "addmm", "matmul",
    "masked_matmul", "relu",
]


class SparseCooTensor:
    """Thin Paddle-shaped wrapper over a BCOO array."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle Tensor-ish surface --------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, -1, -2))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        b = self._bcoo
        if b.dtype == jnp.bool_:
            # BCOO.todense lowers to scatter-add, which rejects bool
            cast = jsparse.BCOO((b.data.astype(jnp.int8), b.indices),
                                shape=b.shape)
            return Tensor(cast.todense().astype(jnp.bool_))
        return Tensor(b.todense())

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    # arithmetic
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


def _as_bcoo(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, jsparse.BCOO):
        return x
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor parity: indices [ndim, nnz]."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor)
                     else indices)
    val = _coerce(values)._value
    if dtype is not None:
        from ..framework.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((val, jnp.asarray(idx.T, jnp.int32)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """paddle.sparse.sparse_csr_tensor parity (converted to COO)."""
    crows = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(np.stack([rows, cols]), values, shape,
                             dtype=dtype)


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x):
    return False  # CSR is normalized to COO at creation


def add(x, y):
    if isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_add(_as_bcoo(x), _as_bcoo(y)) \
            if hasattr(jsparse, "bcoo_add") else (
                _as_bcoo(x) + _as_bcoo(y))
        return SparseCooTensor(out.sum_duplicates())
    return Tensor(_as_bcoo(x).todense() + _coerce(y)._value)


def subtract(x, y):
    if isinstance(y, SparseCooTensor):
        neg = jsparse.BCOO((-_as_bcoo(y).data, _as_bcoo(y).indices),
                           shape=_as_bcoo(y).shape)
        return add(x, SparseCooTensor(neg))
    return Tensor(_as_bcoo(x).todense() - _coerce(y)._value)


def multiply(x, y):
    """Elementwise; sparse × dense keeps sparsity."""
    bx = _as_bcoo(x)
    if isinstance(y, SparseCooTensor):
        return SparseCooTensor(jsparse.bcoo_multiply_sparse(
            bx, _as_bcoo(y)))
    yv = _coerce(y)._value
    if np.ndim(yv) == 0:
        return SparseCooTensor(jsparse.BCOO((bx.data * yv, bx.indices),
                                            shape=bx.shape))
    return SparseCooTensor(jsparse.bcoo_multiply_dense(bx, yv))


def divide(x, y):
    """Elementwise divide; sparse / dense(or scalar) keeps sparsity,
    sparse / sparse densifies (zero / zero is nan in the reference too,
    so only matching patterns are meaningful)."""
    bx = _as_bcoo(x)
    if isinstance(y, SparseCooTensor):
        return Tensor(bx.todense() / _as_bcoo(y).todense())
    yv = _coerce(y)._value
    if np.ndim(yv) == 0:
        return SparseCooTensor(jsparse.BCOO((bx.data / yv, bx.indices),
                                            shape=bx.shape))
    # dense divisor of any rank: same sampling path as multiply
    return SparseCooTensor(jsparse.bcoo_multiply_dense(bx, 1.0 / yv))


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta*input + alpha*(x @ y) where x is sparse (paddle.sparse.addmm)."""
    iv = _coerce(input)._value if not isinstance(input, SparseCooTensor) \
        else _as_bcoo(input).todense()
    yv = _coerce(y)._value if not isinstance(y, SparseCooTensor) \
        else _as_bcoo(y).todense()
    return Tensor(beta * iv + alpha * (_as_bcoo(x) @ yv))


def matmul(x, y):
    """sparse @ dense → dense (paddle.sparse.matmul)."""
    yv = _coerce(y)._value if not isinstance(y, SparseCooTensor) \
        else _as_bcoo(y).todense()
    return Tensor(_as_bcoo(x) @ yv)


def masked_matmul(x, y, mask):
    """(dense @ dense) sampled at mask's sparsity pattern
    (paddle.sparse.masked_matmul — SDDMM)."""
    xv = _coerce(x)._value
    yv = _coerce(y)._value
    bm = _as_bcoo(mask)
    idx = bm.indices  # [nnz, 2]
    rows = idx[:, 0]
    cols = idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=bm.shape))


def relu(x):
    bx = _as_bcoo(x)
    return SparseCooTensor(jsparse.BCOO((jnp.maximum(bx.data, 0),
                                         bx.indices), shape=bx.shape))


# ------------------------------------------------------------- unary ops --
# Parity: python/paddle/sparse/unary.py — elementwise fns that preserve
# f(0) == 0 operate directly on the BCOO value vector (no densify).

def _unary(fn):
    def op(x, name=None):
        b = _as_bcoo(x)
        return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                            shape=b.shape))
    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
expm1 = _unary(jnp.expm1)
neg = _unary(jnp.negative)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)


def pow(x, factor, name=None):
    b = _as_bcoo(x)
    return SparseCooTensor(jsparse.BCOO((jnp.power(b.data, factor),
                                         b.indices), shape=b.shape))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework import dtype as dtypes
    b = _as_bcoo(x)
    data = b.data if value_dtype is None else b.data.astype(
        dtypes.convert_dtype(value_dtype))
    idx = b.indices if index_dtype is None else b.indices.astype(
        dtypes.convert_dtype(index_dtype))
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=b.shape))


def coalesce(x, name=None):
    return SparseCooTensor(_as_bcoo(x).sum_duplicates())


def transpose(x, perm, name=None):
    b = _as_bcoo(x)
    new_shape = tuple(b.shape[p] for p in perm)
    new_idx = b.indices[:, list(perm)]
    return SparseCooTensor(jsparse.BCOO((b.data, new_idx),
                                        shape=new_shape))


def reshape(x, shape, name=None):
    b = _as_bcoo(x)
    flat = jnp.zeros((), jnp.int64)
    strides = []
    acc = 1
    for s in reversed(b.shape):
        strides.append(acc)
        acc *= s
    strides = list(reversed(strides))
    import builtins
    lin = builtins.sum(b.indices[:, d].astype(jnp.int64) * strides[d]
              for d in range(len(b.shape)))
    shape = [int(s) for s in shape]
    n_elem = 1
    for s in b.shape:
        n_elem *= s
    # one -1 allowed
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = n_elem // known
    new_strides = []
    acc = 1
    for s in reversed(shape):
        new_strides.append(acc)
        acc *= s
    new_strides = list(reversed(new_strides))
    cols = []
    rem = lin
    for st in new_strides:
        cols.append((rem // st).astype(jnp.int32))
        rem = rem % st
    new_idx = jnp.stack(cols, axis=1)
    return SparseCooTensor(jsparse.BCOO((b.data, new_idx),
                                        shape=tuple(shape)))


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


isnan = _unary(jnp.isnan)


def mv(x, vec, name=None):
    """Sparse matrix × dense vector (parity: python/paddle/sparse/binary.py
    mv): [*, M, N] @ [N] -> [*, M]."""
    b = _as_bcoo(x)
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(b @ v)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Reduce a sparse tensor (parity: python/paddle/sparse/unary.py
    sparse sum). Returns dense for full reduction (paddle returns a
    0-nnz sparse scalar; dense is the usable equivalent), sparse when an
    axis survives.

    O(nnz): reduces over the stored values/indices directly (a full
    densify would be O(prod(shape)) memory and defeat sparsity)."""
    b = _as_bcoo(x)
    nd = len(b.shape)
    axes = (list(range(nd)) if axis is None
            else [axis] if isinstance(axis, (int, np.integer))
            else list(axis))
    axes = [int(a) + nd if int(a) < 0 else int(a) for a in axes]
    surv = [d for d in range(nd) if d not in axes]
    if not surv:
        out = jnp.sum(b.data, dtype=dtype)
        if keepdim:
            out = out.reshape((1,) * nd)
        return Tensor(out)
    # coalesce duplicate surviving coordinates host-side (indices are
    # concrete outside jit, same pattern as slice below)
    idx = np.asarray(b.indices)[:, surv]
    uniq, inv = np.unique(idx, axis=0, return_inverse=True)
    data = b.data if dtype is None else b.data.astype(dtype)
    out_data = jax.ops.segment_sum(data, jnp.asarray(inv.ravel()),
                                   num_segments=uniq.shape[0])
    if keepdim:
        full = np.zeros((uniq.shape[0], nd), np.int32)
        full[:, surv] = uniq
        new_shape = tuple(1 if d in axes else b.shape[d] for d in range(nd))
        return SparseCooTensor(jsparse.BCOO(
            (out_data, jnp.asarray(full)), shape=new_shape))
    new_shape = tuple(b.shape[d] for d in surv)
    return SparseCooTensor(jsparse.BCOO(
        (out_data, jnp.asarray(uniq.astype(np.int32))), shape=new_shape))


def slice(x, axes, starts, ends, name=None):
    """Slice a sparse COO tensor along `axes` (parity:
    python/paddle/sparse/multiary.py slice): filter coordinates inside
    the window, shift indices to the new origin."""
    b = _as_bcoo(x)
    shape = list(b.shape)
    lo = {int(a): int(s) for a, s in zip(axes, starts)}
    hi = {}
    for a, e in zip(axes, ends):
        a, e = int(a), int(e)
        if e < 0:
            e += shape[a]
        hi[a] = min(e, shape[a])
    for a in list(lo):
        if lo[a] < 0:
            lo[a] += shape[a]
    keep = jnp.ones((b.indices.shape[0],), bool)
    for a in lo:
        col = b.indices[:, a]
        keep = keep & (col >= lo[a]) & (col < hi[a])
    # host-side compaction (indices are concrete outside jit)
    import numpy as _np
    keep_np = _np.asarray(keep)
    idx = _np.asarray(b.indices)[keep_np]
    dat = _np.asarray(b.data)[keep_np]
    for a in lo:
        idx[:, a] -= lo[a]
        shape[a] = hi[a] - lo[a]
    return SparseCooTensor(jsparse.BCOO((jnp.asarray(dat), jnp.asarray(idx)),
                                        shape=tuple(shape)))


def mask_as(x, mask, name=None):
    """Keep x's entries at mask's nonzero coordinate pattern (parity:
    python/paddle/sparse/unary.py mask_as): dense x, sparse mask ->
    sparse with mask's sparsity."""
    m = _as_bcoo(mask)
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    cols = tuple(m.indices[:, d] for d in range(m.indices.shape[1]))
    vals = xv[cols]
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))


__all__ += ["isnan", "mv", "sum", "slice", "mask_as"]

from . import nn  # noqa: E402  (paddle.sparse.nn layers)
