"""paddle.sparse.nn — layers over sparse COO tensors.

Reference parity: python/paddle/sparse/nn/ (ReLU, BatchNorm,
Conv3D/SubmConv3D, MaxPool3D — the point-cloud stack backed by
phi/kernels/sparse/ CUDA gather-scatter kernels).

TPU-native design: the MXU wants dense tiles, and XLA has no ragged
gather-scatter conv, so convolution computes DENSE through
lax.conv_general_dilated and re-sparsifies at the output sites —
SubmConv3D keeps the input's site pattern (the submanifold contract),
Conv3D takes the true nonzero pattern of the dense result. Activations
and norms run on the value vector only (no densify). For the small
active-site counts sparse point-cloud workloads carry, the dense
compute is one fused XLA conv — the sparsity is a storage format here,
not a compute strategy (documented divergence from the CUDA kernels).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.initializer import Uniform
from . import SparseCooTensor, _as_bcoo

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv3D", "SubmConv3D", "MaxPool3D"]


def _map_values(x, fn):
    b = _as_bcoo(x)
    return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                        shape=b.shape))


class ReLU(Layer):
    def forward(self, x):
        return _map_values(x, jax.nn.relu)


class ReLU6(Layer):
    def forward(self, x):
        return _map_values(x, jax.nn.relu6)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return _map_values(
            x, lambda v: jax.nn.leaky_relu(v, self._slope))


class Softmax(Layer):
    """Softmax over the last dense axis of the values (parity:
    paddle.sparse.nn.Softmax on the nonzero entries per row)."""

    def __init__(self, axis=-1):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse softmax supports axis=-1 only")

    def forward(self, x):
        b = _as_bcoo(x).sum_duplicates()
        # group nonzeros by their row (all index columns but the last)
        ncols = b.shape[-1]
        row = sum(b.indices[:, d].astype(jnp.int64) *
                  int(np.prod(b.shape[d + 1:-1], dtype=np.int64) or 1)
                  for d in range(b.indices.shape[1] - 1))
        order = jnp.argsort(row * ncols + b.indices[:, -1].astype(jnp.int64))
        row_s = row[order]
        data_s = b.data[order]
        # segment softmax over rows
        n_rows = 1
        for s in b.shape[:-1]:
            n_rows *= s
        seg_max = jax.ops.segment_max(data_s, row_s, num_segments=n_rows)
        ex = jnp.exp(data_s - seg_max[row_s])
        seg_sum = jax.ops.segment_sum(ex, row_s, num_segments=n_rows)
        out = ex / seg_sum[row_s]
        inv = jnp.argsort(order)
        return SparseCooTensor(jsparse.BCOO((out[inv], b.indices),
                                            shape=b.shape))


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of NDHWC sparse values
    (parity: paddle.sparse.nn.BatchNorm — statistics over active sites
    only, exactly the reference semantics)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC"):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse BatchNorm requires NDHWC")
        self._eps = epsilon
        self._mom = momentum
        self.weight = self.create_parameter(
            [num_features], default_initializer=Uniform(1.0, 1.0))
        self.bias = self.create_parameter(
            [num_features], is_bias=True,
            default_initializer=Uniform(0.0, 0.0))
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        b = _as_bcoo(x)
        v = b.data  # [nnz, C]
        if self.training:
            mean = v.mean(0)
            var = v.var(0)
            m = jnp.asarray(self._mom, mean.dtype)
            self._mean._inplace_update(
                Tensor(self._mean._value * m + mean * (1 - m)))
            self._variance._inplace_update(
                Tensor(self._variance._value * m + var * (1 - m)))
        else:
            mean, var = self._mean._value, self._variance._value
        out = ((v - mean) / jnp.sqrt(var + self._eps)
               * self.weight._value + self.bias._value)
        return SparseCooTensor(jsparse.BCOO((out, b.indices),
                                            shape=b.shape))


class SyncBatchNorm(BatchNorm):
    """Cross-replica stats ride XLA's psum when run under a mesh; on a
    single device this is BatchNorm (parity shim)."""


def _dense_conv(x, weight, bias, stride, padding, dilation, groups):
    """NDHWC sparse -> dense conv via lax (DHWIO weights)."""
    b = _as_bcoo(x)
    dense = b.todense()
    dn = jax.lax.conv_dimension_numbers(dense.shape, weight.shape,
                                        ("NDHWC", "DHWIO", "NDHWC"))
    pad = padding if isinstance(padding, str) else \
        [(p, p) for p in (padding if isinstance(padding, (list, tuple))
                          else [padding] * 3)]
    out = jax.lax.conv_general_dilated(
        dense, weight, window_strides=list(stride),
        padding=pad, rhs_dilation=list(dilation),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + bias
    return out


class Conv3D(Layer):
    """Parity: paddle.sparse.nn.Conv3D (NDHWC). Dense XLA conv +
    re-sparsify at true nonzero sites."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 bias_attr=None, data_format="NDHWC"):
        super().__init__()
        ks = ((kernel_size,) * 3 if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self._stride = ((stride,) * 3 if isinstance(stride, int)
                        else tuple(stride))
        self._padding = padding
        self._dilation = ((dilation,) * 3 if isinstance(dilation, int)
                          else tuple(dilation))
        self._groups = groups
        k = 1.0 / float(np.sqrt(in_channels * np.prod(ks)))
        self.weight = self.create_parameter(
            list(ks) + [in_channels // groups, out_channels],
            default_initializer=Uniform(-k, k))
        self.bias = (self.create_parameter(
            [out_channels], is_bias=True,
            default_initializer=Uniform(-k, k))
            if bias_attr is not False else None)

    def _run(self, x, subm):
        out = _dense_conv(x, self.weight._value,
                          None if self.bias is None else self.bias._value,
                          self._stride, self._padding, self._dilation,
                          self._groups)
        if subm:
            # submanifold: output sites == input sites
            b = _as_bcoo(x)
            idx = b.indices
            site_idx = idx[:, :-1]
            vals = out[tuple(site_idx[:, d] for d in range(
                site_idx.shape[1]))]
            new_idx = site_idx
            co = out.shape[-1]
            # expand channel dim back into COO form [nnz, C] dense block
            return SparseCooTensor(jsparse.BCOO(
                (vals, new_idx), shape=out.shape[:-1] + (co,)))
        return SparseCooTensor(jsparse.BCOO.fromdense(
            out, n_batch=0, n_dense=1))

    def forward(self, x):
        return self._run(x, subm=False)


class SubmConv3D(Conv3D):
    """Parity: paddle.sparse.nn.SubmConv3D — output active sites are
    exactly the input's (submanifold convolution contract)."""

    def forward(self, x):
        return self._run(x, subm=True)


class MaxPool3D(Layer):
    """Parity: paddle.sparse.nn.MaxPool3D (NDHWC): dense reduce_window,
    re-sparsified."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        ks = ((kernel_size,) * 3 if isinstance(kernel_size, int)
              else tuple(kernel_size))
        st = ks if stride is None else (
            (stride,) * 3 if isinstance(stride, int) else tuple(stride))
        pd = ((padding,) * 3 if isinstance(padding, int)
              else tuple(padding))
        self._ks, self._st, self._pd = ks, st, pd

    def forward(self, x):
        dense = _as_bcoo(x).todense()
        neg = (-jnp.inf if jnp.issubdtype(dense.dtype, jnp.floating)
               else jnp.iinfo(dense.dtype).min)
        out = jax.lax.reduce_window(
            dense, neg, jax.lax.max,
            (1,) + self._ks + (1,), (1,) + self._st + (1,),
            ((0, 0),) + tuple((p, p) for p in self._pd) + ((0, 0),))
        out = jnp.where(jnp.isfinite(out), out, 0)
        return SparseCooTensor(jsparse.BCOO.fromdense(
            out, n_batch=0, n_dense=1))
