"""paddle.hub parity (python/paddle/hapi/hub.py): load models from a
hubconf.py in a local directory or a remote repo. This environment has
zero network egress, so source='github'/'gitee' raises with guidance;
the local path is fully functional (that is also the recommended way to
vendor hub models for air-gapped TPU pods)."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def _resolve(repo_dir, source):
    if source == "local":
        return repo_dir
    raise RuntimeError(
        f"paddle.hub source='{source}' needs network access, which this "
        "TPU environment does not have. Clone the repo and use "
        "source='local' with its path.")


def list(repo_dir, source="github", force_reload=False):
    """Entry-point names exported by the repo's hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not in hubconf")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not in hubconf")
    return fn(**kwargs)
