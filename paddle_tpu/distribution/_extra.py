"""Second tier of paddle.distribution (parity:
python/paddle/distribution/{beta,binomial,cauchy,chi2,gamma,dirichlet,
multinomial,multivariate_normal,student_t,continuous_bernoulli,
transform,transformed_distribution}.py). Sampling uses jax.random's
native samplers (reparameterized where jax provides it); log_prob /
entropy are closed-form jnp expressions routed through apply() so
gradients flow to the parameters."""
from __future__ import annotations

import math as pymath

import numpy as np
import jax
import jax.numpy as jnp

from ..ops._dispatch import apply
from ..ops.creation import _coerce
from ..framework.random import next_key
from . import Distribution, _t, _shape

__all__ = [
    "ExponentialFamily", "Beta", "Binomial", "Cauchy",
    "ContinuousBernoulli", "Chi2", "Dirichlet", "Gamma", "Multinomial",
    "MultivariateNormal", "StudentT", "Transform", "AffineTransform",
    "ExpTransform", "SigmoidTransform", "TanhTransform",
    "PowerTransform", "AbsTransform", "ChainTransform",
    "IndependentTransform", "ReshapeTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TransformedDistribution",
    "LKJCholesky",
]


class ExponentialFamily(Distribution):
    """Parity base class: paddle.distribution.ExponentialFamily."""


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(np.broadcast_shapes(self.alpha._value.shape,
                                             self.beta._value.shape))

    @property
    def mean(self):
        return apply(lambda a, b: a / (a + b), self.alpha, self.beta)

    @property
    def variance(self):
        return apply(lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                     self.alpha, self.beta)

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        k = next_key()
        return apply(lambda a, b: jax.random.beta(k, a, b, shp),
                     self.alpha, self.beta)

    def log_prob(self, value):
        def fn(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.betaln(a, b)))
        return apply(fn, _coerce(value), self.alpha, self.beta)

    def entropy(self):
        def fn(a, b):
            dg = jax.scipy.special.digamma
            return (jax.scipy.special.betaln(a, b)
                    - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return apply(fn, self.alpha, self.beta)


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(np.broadcast_shapes(
            self.concentration._value.shape, self.rate._value.shape))

    @property
    def mean(self):
        return apply(lambda c, r: c / r, self.concentration, self.rate)

    @property
    def variance(self):
        return apply(lambda c, r: c / (r * r), self.concentration,
                     self.rate)

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        k = next_key()
        return apply(lambda c, r: jax.random.gamma(k, c, shp) / r,
                     self.concentration, self.rate)

    rsample = sample

    def log_prob(self, value):
        def fn(v, c, r):
            return (c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(c))
        return apply(fn, _coerce(value), self.concentration, self.rate)

    def entropy(self):
        def fn(c, r):
            dg = jax.scipy.special.digamma
            return (c - jnp.log(r) + jax.scipy.special.gammaln(c)
                    + (1 - c) * dg(c))
        return apply(fn, self.concentration, self.rate)


class Chi2(Gamma):
    """Parity: paddle.distribution.Chi2 — Gamma(df/2, 1/2)."""

    def __init__(self, df):
        self.df = _t(df)
        super().__init__(apply(lambda d: d / 2.0, self.df), 0.5)


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration):
        self.concentration = _t(concentration)
        super().__init__(self.concentration._value.shape[:-1],
                         self.concentration._value.shape[-1:])

    @property
    def mean(self):
        return apply(lambda c: c / jnp.sum(c, -1, keepdims=True),
                     self.concentration)

    def sample(self, shape=()):
        # jax.random.dirichlet wants shape = sample_shape + batch_shape
        shp = _shape(shape, self._batch_shape)
        k = next_key()
        return apply(lambda c: jax.random.dirichlet(k, c, shp),
                     self.concentration)

    def log_prob(self, value):
        def fn(v, c):
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + jax.scipy.special.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jax.scipy.special.gammaln(c), -1))
        return apply(fn, _coerce(value), self.concentration)

    def entropy(self):
        def fn(c):
            a0 = jnp.sum(c, -1)
            n = c.shape[-1]
            dg = jax.scipy.special.digamma
            lnB = (jnp.sum(jax.scipy.special.gammaln(c), -1)
                   - jax.scipy.special.gammaln(a0))
            return (lnB + (a0 - n) * dg(a0)
                    - jnp.sum((c - 1) * dg(c), -1))
        return apply(fn, self.concentration)


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc._value.shape,
                                             self.scale._value.shape))

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        k = next_key()
        return apply(lambda l, s: l + s * jax.random.cauchy(k, shp),
                     self.loc, self.scale)

    rsample = sample

    def log_prob(self, value):
        def fn(v, l, s):
            return (-jnp.log(np.float32(pymath.pi)) - jnp.log(s)
                    - jnp.log1p(((v - l) / s) ** 2))
        return apply(fn, _coerce(value), self.loc, self.scale)

    def entropy(self):
        return apply(lambda s: jnp.log(4 * np.float32(pymath.pi) * s),
                     self.scale)

    def cdf(self, value):
        def fn(v, l, s):
            return jnp.arctan((v - l) / s) / np.float32(pymath.pi) + 0.5
        return apply(fn, _coerce(value), self.loc, self.scale)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(
            self.df._value.shape, self.loc._value.shape,
            self.scale._value.shape))

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        k = next_key()
        return apply(lambda d, l, s: l + s * jax.random.t(k, d, shp),
                     self.df, self.loc, self.scale)

    def log_prob(self, value):
        def fn(v, d, l, s):
            z = (v - l) / s
            return (jax.scipy.special.gammaln((d + 1) / 2)
                    - jax.scipy.special.gammaln(d / 2)
                    - 0.5 * jnp.log(d * np.float32(pymath.pi)) - jnp.log(s)
                    - (d + 1) / 2 * jnp.log1p(z * z / d))
        return apply(fn, _coerce(value), self.df, self.loc, self.scale)


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(np.broadcast_shapes(
            self.total_count._value.shape, self.probs._value.shape))

    @property
    def mean(self):
        return apply(lambda n, p: n * p, self.total_count, self.probs)

    @property
    def variance(self):
        return apply(lambda n, p: n * p * (1 - p), self.total_count,
                     self.probs)

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        k = next_key()
        return apply(
            lambda n, p: jax.random.binomial(k, n.astype(jnp.float32),
                                             p, shp),
            self.total_count, self.probs)

    def log_prob(self, value):
        def fn(v, n, p):
            return (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1)
                    + v * jnp.log(p) + (n - v) * jnp.log1p(-p))
        return apply(fn, _coerce(value), self.total_count, self.probs)


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(self.probs._value.shape[:-1],
                         self.probs._value.shape[-1:])

    def sample(self, shape=()):
        shp = tuple(shape)
        k = next_key()

        def fn(p):
            n = self.total_count
            logits = jnp.log(p + 1e-30)
            draws = jax.random.categorical(
                k, logits, axis=-1,
                shape=shp + (n,) + p.shape[:-1])       # [*shp, n, *batch]
            oh = jax.nn.one_hot(draws, p.shape[-1], dtype=p.dtype)
            counts = jnp.sum(oh, axis=len(shp))         # sum over n draws
            return counts
        return apply(fn, self.probs)

    def log_prob(self, value):
        def fn(v, p):
            return (jax.scipy.special.gammaln(jnp.sum(v, -1) + 1)
                    - jnp.sum(jax.scipy.special.gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(p + 1e-30), -1))
        return apply(fn, _coerce(value), self.probs)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = _t(loc)
        if covariance_matrix is not None:
            self.covariance_matrix = _t(covariance_matrix)
            self._tril = apply(jnp.linalg.cholesky, self.covariance_matrix)
        elif scale_tril is not None:
            self._tril = _t(scale_tril)
            self.covariance_matrix = apply(
                lambda t: t @ jnp.swapaxes(t, -1, -2), self._tril)
        elif precision_matrix is not None:
            cov = apply(jnp.linalg.inv, _t(precision_matrix))
            self.covariance_matrix = cov
            self._tril = apply(jnp.linalg.cholesky, cov)
        else:
            raise ValueError("one of covariance_matrix/precision_matrix/"
                             "scale_tril is required")
        super().__init__(self.loc._value.shape[:-1],
                         self.loc._value.shape[-1:])

    @property
    def mean(self):
        return self.loc

    def sample(self, shape=()):
        shp = tuple(shape)
        k = next_key()

        def fn(l, t):
            eps = jax.random.normal(k, shp + l.shape, l.dtype)
            return l + jnp.einsum("...ij,...j->...i", t, eps)
        return apply(fn, self.loc, self._tril)

    rsample = sample

    def log_prob(self, value):
        def fn(v, l, t):
            d = l.shape[-1]
            diff = v - l
            tb = jnp.broadcast_to(t, diff.shape[:-1] + t.shape[-2:])
            sol = jax.scipy.linalg.solve_triangular(tb, diff[..., None],
                                                    lower=True)[..., 0]
            logdet = jnp.sum(jnp.log(jnp.diagonal(t, axis1=-2, axis2=-1)),
                             -1)
            return (-0.5 * jnp.sum(sol * sol, -1) - logdet
                    - 0.5 * d * np.float32(pymath.log(2 * pymath.pi)))
        return apply(fn, _coerce(value), self.loc, self._tril)

    def entropy(self):
        def fn(t):
            d = t.shape[-1]
            logdet = jnp.sum(jnp.log(jnp.diagonal(t, axis1=-2, axis2=-1)),
                             -1)
            return logdet + 0.5 * d * (1 + np.float32(
                pymath.log(2 * pymath.pi)))
        return apply(fn, self._tril)


class ContinuousBernoulli(ExponentialFamily):
    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(self.probs._value.shape)

    def _log_norm(self, p):
        # C(p) = 2 atanh(1-2p) / (1-2p), with the p ~ 0.5 limit -> 2
        near = jnp.logical_and(p > self._lims[0], p < self._lims[1])
        safe = jnp.where(near, 0.4, p)
        c = (jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * safe)))
             - jnp.log(jnp.abs(1 - 2 * safe)))
        taylor = (np.float32(pymath.log(2.0))
                  + 4.0 / 3.0 * (p - 0.5) ** 2)
        return jnp.where(near, taylor, c)

    def log_prob(self, value):
        def fn(v, p):
            return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                    + self._log_norm(p))
        return apply(fn, _coerce(value), self.probs)

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        k = next_key()

        def fn(p):
            u = jax.random.uniform(k, shp, p.dtype)
            near = jnp.logical_and(p > self._lims[0], p < self._lims[1])
            safe = jnp.where(near, 0.4, p)
            s = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                 / (jnp.log(safe) - jnp.log1p(-safe)))
            return jnp.where(near, u, s)
        return apply(fn, self.probs)


# ------------------------------------------------------------- transforms --

class Transform:
    """Parity: paddle.distribution.Transform (forward/inverse +
    log-det-Jacobian)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        from ..ops import math as om
        return om.neg(self.forward_log_det_jacobian(self.inverse(y)))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return apply(lambda v, l, s: l + s * v, _coerce(x), self.loc,
                     self.scale)

    def inverse(self, y):
        return apply(lambda v, l, s: (v - l) / s, _coerce(y), self.loc,
                     self.scale)

    def forward_log_det_jacobian(self, x):
        return apply(lambda v, s: jnp.broadcast_to(
            jnp.log(jnp.abs(s)), v.shape), _coerce(x), self.scale)


class ExpTransform(Transform):
    def forward(self, x):
        return apply(jnp.exp, _coerce(x))

    def inverse(self, y):
        return apply(jnp.log, _coerce(y))

    def forward_log_det_jacobian(self, x):
        return apply(lambda v: v, _coerce(x))


class SigmoidTransform(Transform):
    def forward(self, x):
        return apply(jax.nn.sigmoid, _coerce(x))

    def inverse(self, y):
        return apply(lambda v: jnp.log(v) - jnp.log1p(-v), _coerce(y))

    def forward_log_det_jacobian(self, x):
        return apply(lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v),
                     _coerce(x))


class TanhTransform(Transform):
    def forward(self, x):
        return apply(jnp.tanh, _coerce(x))

    def inverse(self, y):
        return apply(jnp.arctanh, _coerce(y))

    def forward_log_det_jacobian(self, x):
        return apply(
            lambda v: 2.0 * (np.float32(pymath.log(2.0)) - v
                             - jax.nn.softplus(-2.0 * v)), _coerce(x))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def forward(self, x):
        return apply(lambda v, p: jnp.power(v, p), _coerce(x), self.power)

    def inverse(self, y):
        return apply(lambda v, p: jnp.power(v, 1.0 / p), _coerce(y),
                     self.power)

    def forward_log_det_jacobian(self, x):
        return apply(lambda v, p: jnp.log(jnp.abs(p * jnp.power(v, p - 1))),
                     _coerce(x), self.power)


class AbsTransform(Transform):
    def forward(self, x):
        return apply(jnp.abs, _coerce(x))

    def inverse(self, y):
        return apply(lambda v: v, _coerce(y))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        from ..ops import math as om
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else om.add(total, j)
            x = t.forward(x)
        return total


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        j = self.base.forward_log_det_jacobian(x)
        return apply(
            lambda v: _sum_rightmost(v, self.rank),
            _coerce(j))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def forward(self, x):
        def fn(v):
            lead = v.shape[:v.ndim - len(self.in_event_shape)]
            return v.reshape(lead + self.out_event_shape)
        return apply(fn, _coerce(x))

    def inverse(self, y):
        def fn(v):
            lead = v.shape[:v.ndim - len(self.out_event_shape)]
            return v.reshape(lead + self.in_event_shape)
        return apply(fn, _coerce(y))

    def forward_log_det_jacobian(self, x):
        def fn(v):
            lead = v.shape[:v.ndim - len(self.in_event_shape)]
            return jnp.zeros(lead, v.dtype)
        return apply(fn, _coerce(x))


class SoftmaxTransform(Transform):
    def forward(self, x):
        return apply(lambda v: jax.nn.softmax(v, axis=-1), _coerce(x))

    def inverse(self, y):
        return apply(lambda v: jnp.log(v), _coerce(y))


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, x, method):
        from ..ops.manipulation import stack, unbind
        parts = unbind(x, axis=self.axis)
        outs = [getattr(t, method)(p)
                for t, p in zip(self.transforms, parts)]
        return stack(outs, axis=self.axis)

    def forward(self, x):
        return self._map(x, "forward")

    def inverse(self, y):
        return self._map(y, "inverse")

    def forward_log_det_jacobian(self, x):
        return self._map(x, "forward_log_det_jacobian")


class StickBreakingTransform(Transform):
    """R^{K-1} -> simplex interior (parity: paddle's stickbreaking)."""

    def forward_log_det_jacobian(self, x):
        # lower-triangular Jacobian: diag_k = sigmoid'(u_k) *
        # prod_{j<k}(1 - z_j)
        def fn(v):
            k = v.shape[-1]
            offset = jnp.log(jnp.arange(k, 0, -1).astype(v.dtype))
            u = v - offset
            z = jax.nn.sigmoid(u)
            log_sig_prime = -jax.nn.softplus(-u) - jax.nn.softplus(u)
            cum = jnp.cumprod(1 - z, axis=-1)
            log_pad = jnp.concatenate(
                [jnp.zeros_like(cum[..., :1]),
                 jnp.log(cum[..., :-1])], -1)
            return jnp.sum(log_sig_prime + log_pad, -1)
        return apply(fn, _coerce(x))

    def forward(self, x):
        def fn(v):
            k = v.shape[-1]
            offset = jnp.log(jnp.arange(k, 0, -1).astype(v.dtype))
            z = jax.nn.sigmoid(v - offset)
            cum = jnp.cumprod(1 - z, axis=-1)
            pad = jnp.concatenate(
                [jnp.ones_like(cum[..., :1]), cum[..., :-1]], -1)
            head = z * pad
            last = cum[..., -1:]
            return jnp.concatenate([head, last], -1)
        return apply(fn, _coerce(x))

    def inverse(self, y):
        def fn(v):
            k = v.shape[-1] - 1
            cum = 1 - jnp.cumsum(v[..., :-1], -1)
            shifted = jnp.concatenate(
                [jnp.ones_like(cum[..., :1]), cum[..., :-1]], -1)
            z = v[..., :-1] / shifted
            offset = jnp.log(jnp.arange(k, 0, -1).astype(v.dtype))
            return jnp.log(z) - jnp.log1p(-z) + offset
        return apply(fn, _coerce(y))


class TransformedDistribution(Distribution):
    """Parity: paddle.distribution.TransformedDistribution."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = (list(transforms)
                           if isinstance(transforms, (list, tuple))
                           else [transforms])
        super().__init__(getattr(base, "_batch_shape", ()))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        from ..ops import math as om
        y = _t(value)
        lp = None
        for t in reversed(self.transforms):
            x = t.inverse(y)
            j = t.forward_log_det_jacobian(x)
            lp = j if lp is None else om.add(lp, j)
            y = x
        base_lp = self.base.log_prob(y)
        return om.subtract(base_lp, lp)


# ----------------------------------------------------------------- KL ------
from . import register_kl  # noqa: E402


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def fn(a1, b1, a2, b2):
        dg = jax.scipy.special.digamma
        bl = jax.scipy.special.betaln
        return (bl(a2, b2) - bl(a1, b1)
                + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                + (a2 - a1 + b2 - b1) * dg(a1 + b1))
    return apply(fn, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def fn(c1, r1, c2, r2):
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        return ((c1 - c2) * dg(c1) - gl(c1) + gl(c2)
                + c2 * (jnp.log(r1) - jnp.log(r2))
                + c1 * (r2 - r1) / r1)
    return apply(fn, p.concentration, p.rate, q.concentration, q.rate)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def fn(c1, c2):
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        a0 = jnp.sum(c1, -1)
        return (gl(a0) - jnp.sum(gl(c1), -1)
                - gl(jnp.sum(c2, -1)) + jnp.sum(gl(c2), -1)
                + jnp.sum((c1 - c2) * (dg(c1) - dg(a0)[..., None]), -1))
    return apply(fn, p.concentration, q.concentration)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def fn(l1, t1, l2, t2):
        d = l1.shape[-1]
        # KL = 0.5 [ tr(S2^-1 S1) + (m2-m1)^T S2^-1 (m2-m1) - d
        #            + ln det S2 - ln det S1 ]
        m = jax.scipy.linalg.solve_triangular(t2, t1, lower=True)
        tr = jnp.sum(m * m, axis=(-2, -1))
        diff = l2 - l1
        sol = jax.scipy.linalg.solve_triangular(t2, diff[..., None],
                                                lower=True)[..., 0]
        maha = jnp.sum(sol * sol, -1)
        ld1 = jnp.sum(jnp.log(jnp.diagonal(t1, axis1=-2, axis2=-1)), -1)
        ld2 = jnp.sum(jnp.log(jnp.diagonal(t2, axis1=-2, axis2=-1)), -1)
        return 0.5 * (tr + maha - d) + ld2 - ld1
    return apply(fn, p.loc, p._tril, q.loc, q._tril)


class LKJCholesky(Distribution):
    """LKJ distribution over Cholesky factors of correlation matrices
    (parity: python/paddle/distribution/lkj_cholesky.py). Sampling uses
    the onion method (Lewandowski, Kurowicka & Joe 2009); log_prob is the
    standard row-power density over the Cholesky diagonal."""

    def __init__(self, dim=2, concentration=1.0,
                 sample_method="onion"):
        if dim < 2:
            raise ValueError("LKJCholesky requires dim >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError(f"unknown sample_method {sample_method}")
        self.dim = int(dim)
        self.concentration = _t(concentration)
        self.sample_method = sample_method
        super().__init__(self.concentration._value.shape)

    def sample(self, shape=()):
        shp = tuple(shape) + tuple(self._batch_shape)
        d = self.dim
        k1, k2 = jax.random.split(next_key())

        def fn(conc):
            # onion method: grow the factor one row at a time; row i's
            # direction is uniform on the sphere, its radius^2 is
            # Beta(i/2, conc + (d - 1 - i)/2)
            beta_a = jnp.arange(1, d, dtype=jnp.float32) / 2.0
            beta_b = conc[..., None] + (d - 2
                                        - jnp.arange(d - 1)) / 2.0
            r2 = jax.random.beta(k1, beta_a, beta_b,
                                 shp + (d - 1,))            # [..., d-1]
            z = jax.random.normal(k2, shp + (d - 1, d))
            # row i uses the first i+1 coords of its gaussian direction
            mask = (jnp.arange(d) <= jnp.arange(d - 1)[:, None])
            z = z * mask
            z = z / jnp.linalg.norm(z, axis=-1, keepdims=True)
            rows = jnp.sqrt(r2)[..., None] * z               # rows 1..d-1
            diag_extra = jnp.sqrt(1.0 - r2)                  # w_{ii}
            L = jnp.zeros(shp + (d, d), jnp.float32)
            L = L.at[..., 0, 0].set(jnp.float32(1.0))
            L = L.at[..., 1:, :].set(rows.astype(jnp.float32))
            ii = jnp.arange(1, d)
            L = L.at[..., ii, ii].set(diag_extra.astype(jnp.float32))
            return L
        return apply(fn, self.concentration)

    def log_prob(self, value):
        d = self.dim

        def fn(L, conc):
            order = jnp.arange(2, d + 1, dtype=jnp.float32)
            expo = 2.0 * (conc[..., None] - 1.0) + d - order
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            unnorm = jnp.sum(expo * jnp.log(diag), axis=-1)
            # normalizer: per onion row i, the sphere-surface term
            # (i/2)*log(pi) - lgamma(i/2) plus the Beta(i/2, a_i)
            # normalizer with a_i = conc + (d - 1 - i)/2
            i = jnp.arange(1, d, dtype=jnp.float32)
            a = conc[..., None] + (d - 1 - i) / 2.0
            logpi = jnp.float32(pymath.log(pymath.pi))
            logB = (jax.scipy.special.gammaln(i / 2.0)
                    + jax.scipy.special.gammaln(a)
                    - jax.scipy.special.gammaln(i / 2.0 + a))
            lognorm = jnp.sum(i / 2.0 * logpi
                              - jax.scipy.special.gammaln(i / 2.0)
                              + logB, axis=-1)
            return unnorm - lognorm
        return apply(fn, _coerce(value), self.concentration)


def _sum_rightmost(v, k):
    return jnp.sum(v, axis=tuple(range(v.ndim - k, v.ndim)))


class Independent(Distribution):
    """Reinterpret the rightmost `reinterpreted_batch_rank` batch dims of
    a base distribution as event dims (parity:
    python/paddle/distribution/independent.py): log_prob sums over the
    reinterpreted dims, sample passes through."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Distribution):
            raise TypeError(
                f"base must be a Distribution, got {type(base).__name__}")
        k = int(reinterpreted_batch_rank)
        if k < 1 or k > len(base.batch_shape):
            raise ValueError(
                "reinterpreted_batch_rank must be in [1, "
                f"len(base.batch_shape)={len(base.batch_shape)}], got {k}")
        self.base = base
        self._reinterpreted_batch_rank = k
        bs = tuple(base.batch_shape)
        super().__init__(bs[:len(bs) - k],
                         bs[len(bs) - k:] + tuple(base.event_shape))

    @property
    def reinterpreted_batch_rank(self):
        return self._reinterpreted_batch_rank

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        return apply(lambda v: _sum_rightmost(
            v, self._reinterpreted_batch_rank),
            self.base.log_prob(value), _name="independent_log_prob")

    def entropy(self):
        return apply(lambda v: _sum_rightmost(
            v, self._reinterpreted_batch_rank),
            self.base.entropy(), _name="independent_entropy")


__all__.append("Independent")
