"""paddle.distribution parity — probability distributions.

Reference parity: python/paddle/distribution/ (Distribution base,
Normal/Uniform/Bernoulli/Categorical/..., kl_divergence + register_kl).

TPU-native: parameters live as Tensors; sampling draws from the global
generator (paddle.seed) via jax.random; log_prob/entropy are pure jnp
through apply() so they differentiate and jit.
"""
from __future__ import annotations

import math as pymath

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops._dispatch import apply
from ..ops.creation import _coerce
from ..framework.random import next_key

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
    "Exponential", "Laplace", "LogNormal", "Gumbel", "Geometric",
    "Poisson", "kl_divergence", "register_kl",
    "ExponentialFamily", "Beta", "Binomial", "Cauchy", "ContinuousBernoulli", "Chi2", "Dirichlet", "Gamma", "Multinomial", "MultivariateNormal", "StudentT", "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform", "TanhTransform", "PowerTransform", "AbsTransform", "ChainTransform", "IndependentTransform", "ReshapeTransform", "SoftmaxTransform", "StackTransform", "StickBreakingTransform", "TransformedDistribution",
    "LKJCholesky", "Independent",
]


def _v(x):
    return _coerce(x)._value if not isinstance(x, (int, float)) \
        else jnp.asarray(x, jnp.float32)


def _t(x):
    """Coerce to Tensor WITHOUT re-wrapping (keeps tape identity so
    rsample/log_prob gradients reach caller-owned parameters)."""
    if isinstance(x, Tensor):
        return x
    return Tensor(_v(x))


def _shape(sample_shape, batch):
    return tuple(sample_shape) + tuple(batch)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc._value.shape,
                                             self.scale._value.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    def rsample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        eps = jax.random.normal(next_key(), shp, jnp.float32)
        # reparameterized through apply() so grads flow to loc/scale
        return apply(lambda l, s: l + s * eps.astype(s.dtype),
                     self.loc, self.scale, _name="normal_rsample")

    sample = rsample

    def log_prob(self, value):
        def fn(v, loc, scale):
            var = scale * scale
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * np.float32(pymath.log(2 * pymath.pi)))
        return apply(fn, _coerce(value), self.loc, self.scale,
                     _name="normal_log_prob")

    def entropy(self):
        return apply(lambda s: 0.5 + 0.5 * np.float32(pymath.log(2 * pymath.pi))
                     + jnp.log(s), self.scale, _name="normal_entropy")

    def cdf(self, value):
        return apply(lambda v, loc, s: 0.5 * (1 + jax.scipy.special.erf(
            (v - loc) / (s * np.float32(pymath.sqrt(2.0))))),
            _coerce(value), self.loc, self.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(np.broadcast_shapes(self.low._value.shape,
                                             self.high._value.shape))

    def rsample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        u = jax.random.uniform(next_key(), shp, jnp.float32)
        return Tensor(self.low._value
                      + (self.high._value - self.low._value) * u)

    sample = rsample

    def log_prob(self, value):
        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo),
                             np.float32(-np.inf))
        return apply(fn, _coerce(value), self.low, self.high)

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _t(probs)
            self.logits = Tensor(jnp.log(self.probs._value)
                                 - jnp.log1p(-self.probs._value))
        else:
            self.logits = _t(logits)
            self.probs = Tensor(jax.nn.sigmoid(self.logits._value))
        super().__init__(self.probs._value.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return Tensor(self.probs._value * (1 - self.probs._value))

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        return Tensor(jax.random.bernoulli(
            next_key(), self.probs._value, shp).astype(jnp.float32))

    def log_prob(self, value):
        def fn(v, logits):
            return v * jax.nn.log_sigmoid(logits) \
                + (1 - v) * jax.nn.log_sigmoid(-logits)
        return apply(fn, _coerce(value), self.logits)

    def entropy(self):
        def fn(p):
            q = 1 - p
            return -(p * jnp.log(jnp.clip(p, 1e-12))
                     + q * jnp.log(jnp.clip(q, 1e-12)))
        return apply(fn, self.probs)


class Categorical(Distribution):
    """paddle.distribution.Categorical parity: `logits` are
    NON-NEGATIVE unnormalized probabilities, normalized by their SUM
    (upstream categorical.py divides by sum everywhere; its doc example
    draws them from paddle.rand) — NOT softmax'd log-space scores
    (r5 fuzz find: the old softmax reading diverged for the documented
    positional usage). The torch-style `probs=` kwarg is an alias with
    the same normalization.

    Negative/zero weights are NOT rejected (upstream normalizes whatever
    it gets); set FLAGS_check_distribution_args=1 to get a construction-
    time warning — that debug path reads the weights onto the host,
    which blocks on device arrays, so it stays off in production."""

    def __init__(self, logits=None, probs=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        raw = logits if logits is not None else probs
        src = _t(raw)
        # Weight validation is DEBUG-ONLY (FLAGS_check_distribution_args):
        # upstream paddle normalizes whatever it is given, so code ported
        # from upstream passing raw scores must not hard-fail here, and
        # np.asarray on a device array is a blocking host transfer we do
        # not pay at construction by default (ADVICE r5 #2 downgraded the
        # r5 ValueError; the log-space-mistake guard is now a warning
        # under the flag). Traced values always skip it.
        from ..framework.flags import flag_value
        if flag_value("check_distribution_args"):
            import jax.core as _jcore
            if not isinstance(src._value, _jcore.Tracer):
                w = np.asarray(src._value)  # host sync: debug flag only
                if (w < 0).any() or (w.sum(-1) == 0).any():
                    import warnings
                    warnings.warn(
                        "Categorical weights should be non-negative with "
                        "a positive sum (they are normalized by their "
                        "sum; log-space scores belong in softmax(logits) "
                        "first). Normalizing anyway for upstream parity.",
                        UserWarning, stacklevel=2)
        # normalization goes through apply() so log_prob/entropy
        # gradients reach a caller-owned weight tensor (advisor r5)
        self.probs = apply(
            lambda w: w / jnp.sum(w, axis=-1, keepdims=True), src)
        self.logits = apply(
            lambda p: jnp.log(jnp.clip(p, 1e-12)), self.probs)
        super().__init__(self.logits._value.shape[:-1])

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        return Tensor(jax.random.categorical(
            next_key(), self.logits._value, axis=-1, shape=shp))

    def log_prob(self, value):
        def fn(v, logits):
            lp = jax.nn.log_softmax(logits, axis=-1)
            vi = v.astype(jnp.int32)
            batch = lp.shape[:-1]
            if not batch:
                # unbatched distribution, any-shaped value: plain gather
                # (take_along_axis needed matching ranks — r5 fuzz find)
                return jnp.take(lp, vi)
            vb = jnp.broadcast_to(
                vi, jnp.broadcast_shapes(vi.shape, batch))
            lpb = jnp.broadcast_to(lp, vb.shape + lp.shape[-1:])
            return jnp.take_along_axis(lpb, vb[..., None],
                                       axis=-1)[..., 0]
        return apply(fn, _coerce(value), self.logits)

    def entropy(self):
        def fn(logits):
            lp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(lp) * lp, axis=-1)
        return apply(fn, self.logits)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._value.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate._value)

    def rsample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        return Tensor(jax.random.exponential(next_key(), shp, jnp.float32)
                      / self.rate._value)

    sample = rsample

    def log_prob(self, value):
        return apply(lambda v, r: jnp.log(r) - r * v,
                     _coerce(value), self.rate)

    def entropy(self):
        return apply(lambda r: 1.0 - jnp.log(r), self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc._value.shape,
                                             self.scale._value.shape))

    def rsample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        return Tensor(self.loc._value + self.scale._value
                      * jax.random.laplace(next_key(), shp, jnp.float32))

    sample = rsample

    def log_prob(self, value):
        return apply(lambda v, m, b: -jnp.abs(v - m) / b
                     - jnp.log(2 * b), _coerce(value), self.loc, self.scale)

    def entropy(self):
        return apply(lambda b: 1.0 + jnp.log(2 * b), self.scale)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._normal = Normal(loc, scale)
        self.loc = self._normal.loc
        self.scale = self._normal.scale
        super().__init__(self._normal._batch_shape)

    def rsample(self, shape=()):
        return apply(jnp.exp, self._normal.rsample(shape))

    sample = rsample

    def log_prob(self, value):
        logv = apply(jnp.log, _coerce(value))
        return self._normal.log_prob(logv) - logv

    def entropy(self):
        return self._normal.entropy() + self.loc


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc._value.shape,
                                             self.scale._value.shape))

    def rsample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        return Tensor(self.loc._value + self.scale._value
                      * jax.random.gumbel(next_key(), shp, jnp.float32))

    sample = rsample

    def log_prob(self, value):
        def fn(v, m, b):
            z = (v - m) / b
            return -(z + jnp.exp(-z)) - jnp.log(b)
        return apply(fn, _coerce(value), self.loc, self.scale)

    def entropy(self):
        return apply(lambda b: jnp.log(b) + np.float32(1.5772156649),
                     self.scale)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(self.probs._value.shape)

    def sample(self, shape=()):
        # paddle.distribution.Geometric uses the FAILURES convention
        # (support {0, 1, ...}, pmf (1-p)^k p); jax.random.geometric
        # samples trials on {1, 2, ...} — shift down by one
        shp = _shape(shape, self._batch_shape)
        return Tensor((jax.random.geometric(
            next_key(), self.probs._value, shp) - 1).astype(jnp.float32))

    def log_prob(self, value):
        return apply(lambda v, p: v * jnp.log1p(-p) + jnp.log(p),
                     _coerce(value), self.probs)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._value.shape)

    def sample(self, shape=()):
        shp = _shape(shape, self._batch_shape)
        return Tensor(jax.random.poisson(
            next_key(), self.rate._value, shp).astype(jnp.float32))

    def log_prob(self, value):
        return apply(lambda v, r: v * jnp.log(r) - r
                     - jax.scipy.special.gammaln(v + 1),
                     _coerce(value), self.rate)


# -- KL registry -----------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator mirroring paddle.distribution.register_kl."""
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__}); "
        "use @register_kl to add the pair")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def fn(pl, ps, ql, qs):
        vr = (ps / qs) ** 2
        return 0.5 * (vr + ((pl - ql) / qs) ** 2 - 1.0 - jnp.log(vr))
    return apply(fn, p.loc, p.scale, q.loc, q.scale, _name="kl_normal")


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def fn(plo, phi, qlo, qhi):
        out = jnp.log((qhi - qlo) / (phi - plo))
        inside = (qlo <= plo) & (phi <= qhi)
        return jnp.where(inside, out, np.float32(np.inf))
    return apply(fn, p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pp, qp):
        t1 = pp * (jnp.log(jnp.clip(pp, 1e-12))
                   - jnp.log(jnp.clip(qp, 1e-12)))
        t2 = (1 - pp) * (jnp.log(jnp.clip(1 - pp, 1e-12))
                         - jnp.log(jnp.clip(1 - qp, 1e-12)))
        return t1 + t2
    return apply(fn, p.probs, q.probs)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def fn(pl, ql):
        plog = jax.nn.log_softmax(pl, axis=-1)
        qlog = jax.nn.log_softmax(ql, axis=-1)
        return jnp.sum(jnp.exp(plog) * (plog - qlog), axis=-1)
    return apply(fn, p.logits, q.logits)


from ._extra import *  # noqa: F401,F403,E402  (second-tier distributions)
