"""ctypes bindings for the native runtime library (csrc/).

Reference parity: the C++ runtime layer of the reference —
paddle/phi/core/distributed/store/tcp_store.cc (TCPStore),
paddle/phi/core/flags.cc (flag registry), paddle/fluid/memory stats, and the
DataLoader shared-memory worker path. pybind11 is not in this image, so the
boundary is a C ABI loaded via ctypes.

The library auto-builds from csrc/ on first import when the .so is missing or
stale (source mtime newer); builds take <5s with the baked-in g++.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_CSRC = os.path.join(_REPO, "csrc")
_SO = os.path.join(_HERE, "libpaddle_tpu_rt.so")

_lib = None
_build_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


# sources of the separate capi library (make capi) — not inputs of the
# core runtime .so, so they must not trigger its staleness/rebuild
_CAPI_ONLY = ("capi.cc", "pd_inference_c_api.h")


def _needs_build() -> bool:
    if not os.path.isdir(_CSRC):
        return not os.path.exists(_SO)  # prebuilt .so without sources is fine
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    for f in os.listdir(_CSRC):
        if f.endswith((".cc", ".h")) and f not in _CAPI_ONLY:
            if os.path.getmtime(os.path.join(_CSRC, f)) > so_m:
                return True
    return False


def _build():
    """Compile to a temp file and atomically rename, under an flock, so
    concurrently launched ranks never dlopen a half-written .so."""
    import fcntl
    lock_path = _SO + ".lock"
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            if not _needs_build():  # another process built it while we waited
                return
            # capi.cc links libpython and builds separately (make capi);
            # the core runtime lib must stay python-free
            srcs = [os.path.join(_CSRC, f) for f in sorted(os.listdir(_CSRC))
                    if f.endswith(".cc") and f not in _CAPI_ONLY]
            tmp = f"{_SO}.tmp.{os.getpid()}"
            cmd = ["g++", "-O2", "-std=c++17", "-fPIC",
                   "-fvisibility=hidden", "-Wall", "-pthread", "-shared",
                   "-o", tmp] + srcs + ["-lrt"]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _SO)
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def load():
    """Load (building if needed) the native library; raises NativeUnavailable
    if the toolchain or sources are missing."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.isdir(_CSRC) and not os.path.exists(_SO):
            raise NativeUnavailable("csrc/ missing and no prebuilt .so")
        try:
            if _needs_build():
                _build()
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise NativeUnavailable(f"native build failed: {detail}") from e
        lib = ctypes.CDLL(_SO)
        _declare(lib)
        _lib = lib
    # Mirror any flags defined before the lib was loaded (deferred so plain
    # `import paddle_tpu` never pays a compile).
    try:
        from ..framework import flags as _flags
        _flags.resync_native()
    except Exception:
        pass
    return _lib


def is_loaded() -> bool:
    return _lib is not None


def available() -> bool:
    try:
        load()
        return True
    except NativeUnavailable:
        return False


def _declare(lib):
    c = ctypes
    lib.pd_last_error.restype = c.c_char_p
    lib.pd_free.argtypes = [c.c_void_p]
    # flags
    lib.pd_flag_define.argtypes = [c.c_char_p, c.c_int, c.c_char_p,
                                   c.c_double, c.c_char_p]
    lib.pd_flag_set_num.argtypes = [c.c_char_p, c.c_double]
    lib.pd_flag_set_str.argtypes = [c.c_char_p, c.c_char_p]
    lib.pd_flag_get_num.argtypes = [c.c_char_p]
    lib.pd_flag_get_num.restype = c.c_double
    lib.pd_flag_get_str.argtypes = [c.c_char_p]
    lib.pd_flag_get_str.restype = c.c_void_p  # manual decode+free
    # stats
    for fn in ("pd_stats_record_alloc", "pd_stats_record_free"):
        getattr(lib, fn).argtypes = [c.c_char_p, c.c_int64]
    for fn in ("pd_stats_current", "pd_stats_peak", "pd_stats_alloc_count"):
        getattr(lib, fn).argtypes = [c.c_char_p]
        getattr(lib, fn).restype = c.c_int64
    lib.pd_stats_reset_peak.argtypes = [c.c_char_p]
    # tcp store
    lib.pd_store_server_start.argtypes = [c.c_int]
    lib.pd_store_server_start.restype = c.c_void_p
    lib.pd_store_server_port.argtypes = [c.c_void_p]
    lib.pd_store_server_stop.argtypes = [c.c_void_p]
    lib.pd_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pd_store_client_connect.restype = c.c_void_p
    lib.pd_store_client_free.argtypes = [c.c_void_p]
    lib.pd_store_set.argtypes = [c.c_void_p, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_int64]
    lib.pd_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int,
                                 c.POINTER(c.POINTER(c.c_uint8)),
                                 c.POINTER(c.c_int64)]
    lib.pd_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pd_store_add.restype = c.c_int64
    lib.pd_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.pd_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pd_store_delete.restype = c.c_int64
    lib.pd_store_num_keys.argtypes = [c.c_void_p]
    lib.pd_store_num_keys.restype = c.c_int64
    # shm channel
    lib.pd_shm_create.argtypes = [c.c_char_p, c.c_int64]
    lib.pd_shm_create.restype = c.c_void_p
    lib.pd_shm_open.argtypes = [c.c_char_p]
    lib.pd_shm_open.restype = c.c_void_p
    lib.pd_shm_push.argtypes = [c.c_void_p, c.POINTER(c.c_uint8), c.c_int64,
                                c.c_int]
    lib.pd_shm_pop.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_uint8)),
                               c.c_int]
    lib.pd_shm_pop.restype = c.c_int64
    lib.pd_shm_close_write.argtypes = [c.c_void_p]
    lib.pd_shm_free.argtypes = [c.c_void_p, c.c_int]
    # host alloc
    lib.pd_host_alloc.argtypes = [c.c_int64, c.c_char_p]
    lib.pd_host_alloc.restype = c.c_void_p
    lib.pd_host_free.argtypes = [c.c_void_p, c.c_int64, c.c_char_p]


def _err(lib) -> str:
    return lib.pd_last_error().decode(errors="replace")


# ------------------------------------------------------------- TCPStore ---
class TCPStore:
    """Rendezvous KV store (parity: paddle.distributed.TCPStore /
    phi TCPStore). is_master starts the in-process server daemon; every
    rank (master included) talks through a client connection."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 90.0):
        lib = load()
        self._lib = lib
        self._server = None
        self.host = host
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = lib.pd_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore server: {_err(lib)}")
            port = lib.pd_store_server_port(self._server)
        self.port = port
        self._client = lib.pd_store_client_connect(
            host.encode(), port, self.timeout_ms)
        if not self._client:
            raise RuntimeError(f"TCPStore connect: {_err(lib)}")
        self.world_size = world_size

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value)
        rc = self._lib.pd_store_set(self._client, key.encode(), buf,
                                    len(value))
        if rc != 0:
            raise RuntimeError(f"TCPStore.set: {_err(self._lib)}")

    def get(self, key: str, timeout_ms: int | None = None) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.pd_store_get(
            self._client, key.encode(),
            self.timeout_ms if timeout_ms is None else timeout_ms,
            ctypes.byref(out), ctypes.byref(n))
        if rc != 0:
            raise KeyError(f"TCPStore.get({key!r}): {_err(self._lib)}")
        data = ctypes.string_at(out, n.value)
        self._lib.pd_free(out)
        return data

    def add(self, key: str, delta: int) -> int:
        v = self._lib.pd_store_add(self._client, key.encode(), delta)
        if v == -(2 ** 63):
            raise RuntimeError(f"TCPStore.add: {_err(self._lib)}")
        return v

    def wait(self, keys, timeout_ms: int | None = None) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            rc = self._lib.pd_store_wait(
                self._client, k.encode(),
                self.timeout_ms if timeout_ms is None else timeout_ms)
            if rc != 0:
                raise TimeoutError(f"TCPStore.wait({k!r}) timed out")

    def delete_key(self, key: str) -> bool:
        return self._lib.pd_store_delete(self._client, key.encode()) > 0

    def num_keys(self) -> int:
        return self._lib.pd_store_num_keys(self._client)

    def barrier(self, name: str, world_size: int | None = None,
                timeout_ms: int | None = None) -> None:
        """All ranks add 1 then wait for the count to reach world_size."""
        ws = world_size or self.world_size
        n = self.add(f"__barrier/{name}", 1)
        if n >= ws:
            self.set(f"__barrier/{name}/done", b"1")
        self.wait(f"__barrier/{name}/done", timeout_ms)

    def close(self) -> None:
        if self._client:
            self._lib.pd_store_client_free(self._client)
            self._client = None
        if self._server:
            self._lib.pd_store_server_stop(self._server)
            self._server = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass


# ----------------------------------------------------------- ShmChannel ---
class ShmChannel:
    """Bounded byte-message channel in POSIX shared memory (parity: the
    reference DataLoader's use_shared_memory worker transport)."""

    def __init__(self, name: str, capacity: int = 64 << 20,
                 create: bool = False):
        lib = load()
        self._lib = lib
        self.name = name
        self._owner = create
        if create:
            self._h = lib.pd_shm_create(name.encode(), capacity)
        else:
            self._h = lib.pd_shm_open(name.encode())
        if not self._h:
            raise RuntimeError(f"ShmChannel({name!r}): {_err(lib)}")

    def push(self, data: bytes, timeout_ms: int = 60000) -> None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        rc = self._lib.pd_shm_push(self._h, buf, len(data), timeout_ms)
        if rc != 0:
            raise RuntimeError(f"ShmChannel.push: {_err(self._lib)}")

    def pop(self, timeout_ms: int = 60000):
        """Returns bytes, or None when the channel is closed and drained."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.pd_shm_pop(self._h, ctypes.byref(out), timeout_ms)
        if n == -3:
            return None
        if n < 0:
            raise TimeoutError(f"ShmChannel.pop: {_err(self._lib)}")
        data = ctypes.string_at(out, n)
        self._lib.pd_free(out)
        return data

    def push_obj(self, obj, timeout_ms: int = 60000) -> None:
        self.push(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                  timeout_ms)

    def pop_obj(self, timeout_ms: int = 60000):
        data = self.pop(timeout_ms)
        return None if data is None else pickle.loads(data)

    def close_write(self) -> None:
        self._lib.pd_shm_close_write(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.pd_shm_free(self._h, 1 if self._owner else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------------------------- stats API ---
def stats_current(pool: str = "host") -> int:
    return load().pd_stats_current(pool.encode())


def stats_peak(pool: str = "host") -> int:
    return load().pd_stats_peak(pool.encode())


def stats_alloc_count(pool: str = "host") -> int:
    return load().pd_stats_alloc_count(pool.encode())


def stats_reset_peak(pool: str = "host") -> None:
    load().pd_stats_reset_peak(pool.encode())


def record_alloc(pool: str, nbytes: int) -> None:
    load().pd_stats_record_alloc(pool.encode(), nbytes)


def record_free(pool: str, nbytes: int) -> None:
    load().pd_stats_record_free(pool.encode(), nbytes)


# ------------------------------------------------------- native flags ---
FLAG_BOOL, FLAG_INT, FLAG_DOUBLE, FLAG_STRING = 0, 1, 2, 3


def flag_define(name: str, type_code: int, str_default: str = "",
                num_default: float = 0.0, help_: str = "") -> bool:
    """Returns True if an env var FLAGS_<name> overrode the default."""
    return bool(load().pd_flag_define(
        name.encode(), type_code, str_default.encode(), num_default,
        help_.encode()))


def flag_set(name: str, value) -> None:
    lib = load()
    if isinstance(value, str):
        rc = lib.pd_flag_set_str(name.encode(), value.encode())
    else:
        rc = lib.pd_flag_set_num(name.encode(), float(value))
    if rc != 0:
        raise KeyError(_err(lib))


def flag_get_num(name: str) -> float:
    return load().pd_flag_get_num(name.encode())


def flag_get_str(name: str):
    lib = load()
    p = lib.pd_flag_get_str(name.encode())
    if not p:
        return None
    s = ctypes.string_at(p).decode()
    lib.pd_free(p)
    return s
