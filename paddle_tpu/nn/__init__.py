"""paddle.nn parity namespace (python/paddle/nn/__init__.py)."""
from __future__ import annotations

from .layer_base import Layer
from . import functional
from . import initializer
from . import utils
from . import quant
from .initializer import ParamAttr
from .layers_common import (
    Sequential, LayerList, LayerDict, ParameterList,
    Linear,
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
    Embedding,
    Dropout, Dropout2D, Dropout3D, AlphaDropout, FeatureAlphaDropout,
    ReLU, ReLU6, Sigmoid, LogSigmoid, Tanh, Tanhshrink, Hardshrink,
    Hardsigmoid, Hardswish, Hardtanh, Softshrink, Softsign, Swish, Silu, Mish,
    SELU, CELU, ELU, GELU, LeakyReLU, Softplus, Maxout, GLU, Softmax,
    LogSoftmax, PReLU, RReLU, Softmax2D, ThresholdedReLU,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    LPPool1D, LPPool2D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    FractionalMaxPool2D, FractionalMaxPool3D,
    Pad1D, Pad2D, Pad3D, ZeroPad1D, ZeroPad2D, ZeroPad3D,
    Flatten, Identity, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, PixelUnshuffle, Unfold, CosineSimilarity, Bilinear,
    Fold, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D, PairwiseDistance,
    Unflatten, ChannelShuffle,
)
from .transformer import (
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .decode import BeamSearchDecoder, dynamic_decode
from .losses import (
    AdaptiveLogSoftmaxWithLoss,
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, CosineEmbeddingLoss,
    TripletMarginLoss, HingeEmbeddingLoss,
    CTCLoss, RNNTLoss, SoftMarginLoss, MultiLabelSoftMarginLoss, MultiMarginLoss,
    TripletMarginWithDistanceLoss, PoissonNLLLoss, GaussianNLLLoss,
)
from .rnn import (
    SimpleRNN, LSTM, GRU, LSTMCell, GRUCell, SimpleRNNCell, RNNBase,
    RNN, BiRNN, RNNCellBase,
)
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm
