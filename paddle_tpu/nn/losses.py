"""Loss layers (parity: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .layer_base import Layer
from . import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CTCLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py CTCLoss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        from .functional import ctc_loss
        return ctc_loss(log_probs, labels, input_lengths, label_lengths,
                        blank=self.blank, reduction=self.reduction,
                        norm_by_times=norm_by_times)


class SoftMarginLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py SoftMarginLoss."""

    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        from .functional_extra import soft_margin_loss
        return soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py MultiLabelSoftMarginLoss."""

    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        from .functional_extra import multi_label_soft_margin_loss
        return multi_label_soft_margin_loss(input, label, self.weight,
                                            self.reduction)


class MultiMarginLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py MultiMarginLoss."""

    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        from .functional_extra import multi_margin_loss
        return multi_margin_loss(input, label, self.p, self.margin,
                                 self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py
    TripletMarginWithDistanceLoss."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        from .functional_extra import triplet_margin_with_distance_loss
        return triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class PoissonNLLLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py PoissonNLLLoss."""

    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        from .functional_extra import poisson_nll_loss
        return poisson_nll_loss(input, label, self.log_input, self.full,
                                self.epsilon, self.reduction)


class GaussianNLLLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py GaussianNLLLoss."""

    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        from .functional_extra import gaussian_nll_loss
        return gaussian_nll_loss(input, label, variance, self.full,
                                 self.epsilon, self.reduction)


class RNNTLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        from .functional_extra import rnnt_loss
        return rnnt_loss(input, label, input_lengths, label_lengths,
                         blank=self.blank,
                         fastemit_lambda=self.fastemit_lambda,
                         reduction=self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Parity: python/paddle/nn/layer/loss.py AdaptiveLogSoftmaxWithLoss
    (Grave et al., "Efficient softmax approximation for GPUs")."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (not cutoffs or cutoffs != sorted(set(cutoffs))
                or cutoffs[-1] > n_classes - 1):
            raise ValueError("cutoffs must be unique, sorted, < n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        n_clusters = len(self.cutoffs) - 1
        head_size = self.cutoffs[0] + n_clusters
        self.head_weight = self.create_parameter([in_features, head_size])
        self.head_bias = (self.create_parameter([head_size], is_bias=True)
                         if head_bias else None)
        self.tail_weights = []
        for i in range(n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz])
            cls = self.create_parameter([hsz, osz])
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_cls_{i}", cls)
            self.tail_weights.append((proj, cls))

    def forward(self, input, label):
        from .functional_extra import adaptive_log_softmax_with_loss
        return adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probability table."""
        import jax
        import jax.numpy as jnp
        from ..ops._dispatch import apply
        from ..ops.creation import _coerce
        n_clusters = len(self.cutoffs) - 1
        shortlist = self.cutoffs[0]
        args = [_coerce(input), _coerce(self.head_weight)]
        for pr, cl in self.tail_weights:
            args += [_coerce(pr), _coerce(cl)]
        if self.head_bias is not None:
            args.append(_coerce(self.head_bias))
        cutoffs = self.cutoffs
        has_bias = self.head_bias is not None

        def fn(x, hw, *rest):
            tails = rest[:2 * n_clusters]
            hb = rest[2 * n_clusters] if has_bias else None
            head = x @ hw
            if hb is not None:
                head = head + hb
            head_lp = jax.nn.log_softmax(head, axis=-1)
            parts = [head_lp[:, :shortlist]]
            for i in range(n_clusters):
                proj, cls = tails[2 * i], tails[2 * i + 1]
                clus_lp = jax.nn.log_softmax((x @ proj) @ cls, axis=-1)
                parts.append(head_lp[:, shortlist + i][:, None] + clus_lp)
            return jnp.concatenate(parts, axis=1)
        return apply(fn, *args, _name="adaptive_log_prob")

    def predict(self, input):
        from ..ops import search
        return search.argmax(self.log_prob(input), axis=1)
