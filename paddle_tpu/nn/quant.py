"""paddle.nn.quant — weight-only quantization for serving (parity:
python/paddle/nn/quant/quantized_linear.py weight_quantize /
weight_dequantize / weight_only_linear; upstream phi weight_only_linear
kernels).

TPU-native design: int8/int4 weights live in HBM at 1/2 - 1/4 the bf16
footprint; dequantization is expressed as (int -> float cast) * scale
right before the matmul, which XLA fuses into the dot's operand load —
the MXU still sees a dense (b)f16 contraction, so there is no custom
kernel to write, just the storage format."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops._dispatch import apply
from ..ops.creation import _coerce

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]


def _check_algo(algo):
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported quant algo {algo!r}")


def _group_check(n_in, group_size):
    if group_size == -1:
        return
    if group_size < 2 or group_size % 2 or n_in % group_size:
        raise ValueError(
            f"group_size {group_size} must be even and divide the in "
            f"dim {n_in} (use -1 for per-channel scales)")


def weight_quantize(x, algo="weight_only_int8", group_size=-1):
    """Absmax quantization of a [in, out] weight. group_size=-1: one
    scale per output channel, scale [out]; group_size=g: one scale per
    (g-row in-dim block, output channel), scale [in//g, out] — the
    finer-grained scheme GPTQ/AWQ checkpoints use. int4 packs two
    nibbles per int8 byte along the in dim (row-major pairs; g is even,
    so pairs never straddle a group boundary)."""
    _check_algo(algo)
    w = np.asarray(_coerce(x)._value, np.float32)
    _group_check(w.shape[0], group_size)
    if group_size == -1:
        absmax = np.maximum(np.abs(w).max(axis=0), 1e-8)     # [out]
        row_max = absmax                                     # bcasts [in,out]
    else:
        g = group_size
        wg = w.reshape(w.shape[0] // g, g, w.shape[1])
        absmax = np.maximum(np.abs(wg).max(axis=1), 1e-8)    # [in//g, out]
        row_max = np.repeat(absmax, g, axis=0)               # [in, out]
    if algo == "weight_only_int4":
        q = np.clip(np.round(w / row_max * 7.0), -8, 7).astype(np.int8)
        if q.shape[0] % 2:
            q = np.concatenate([q, np.zeros((1, q.shape[1]), np.int8)])
        lo = q[0::2] & 0x0F
        hi = (q[1::2] & 0x0F) << 4
        packed = (lo | hi).astype(np.int8)             # [ceil(in/2), out]
        scale = absmax / 7.0
    else:
        q = np.clip(np.round(w / row_max * 127.0),
                    -127, 127).astype(np.int8)
        packed = q
        scale = absmax / 127.0
    return Tensor(jnp.asarray(packed)), Tensor(jnp.asarray(scale))


def _unpack_int4(packed, in_features=None):
    """Unpack nibble pairs; `in_features` strips the odd-in-dim pad row
    the packer added."""
    lo = (packed & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo)               # sign-extend
    hi = ((packed.astype(jnp.uint8) >> 4) & 0x0F).astype(jnp.int8)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=1)                  # [n, 2, out]
    out = out.reshape(packed.shape[0] * 2, packed.shape[1])
    if in_features is not None:
        out = out[:in_features]
    return out


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float32", group_size=-1):
    """Inverse of weight_quantize (float reconstruction). int4 packs in
    pairs along the in dim, so an odd original in-dim comes back with
    one trailing zero pad row — slice to the original shape if needed
    (weight_only_linear strips it automatically)."""
    _check_algo(algo)

    if group_size != -1:
        n_groups = int(_coerce(scale)._value.shape[0])
        _group_check(n_groups * group_size, group_size)

    def fn(q, s):
        if algo == "weight_only_int4":
            w = _unpack_int4(q)
        else:
            w = q
        if group_size != -1:
            # grouped quantization requires an even group dividing the in
            # dim, so the unpacked weight has exactly n_groups*g rows
            if s.shape[0] * group_size != w.shape[0]:
                raise ValueError(
                    f"group_size {group_size} x {s.shape[0]} scale "
                    f"groups covers {s.shape[0] * group_size} rows, but "
                    f"the weight has {w.shape[0]} — pass the group_size "
                    "used at quantization")
            s = jnp.repeat(s, group_size, axis=0)
        return (w.astype(jnp.float32) * s).astype(out_dtype)
    return apply(fn, _coerce(x), _coerce(scale), _name="weight_dequant")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", group_size=-1, name=None):
    """y = x @ dequant(weight) + bias. The dequant-cast-scale chain sits
    directly on the dot operand so XLA fuses it; weights stay int in
    HBM (the point of weight-only serving: memory-bandwidth-bound decode
    reads 1/2 - 1/4 the bytes)."""
    if weight_scale is None:
        raise ValueError("weight_only_linear requires weight_scale")
    args = [_coerce(x), _coerce(weight), _coerce(weight_scale)]
    has_bias = bias is not None
    if has_bias:
        args.append(_coerce(bias))
    in_features = int(_coerce(x)._value.shape[-1])
    _group_check(in_features, group_size)

    def fn(v, q, s, *rest):
        if weight_dtype == "int4":
            w = _unpack_int4(q, in_features)
        else:
            w = q
        if group_size != -1:
            # s: [in//g, out] — expand to per-row scales
            s = jnp.repeat(s, group_size, axis=0)
        w = (w.astype(jnp.float32) * s).astype(v.dtype)
        y = v @ w
        if rest:
            y = y + rest[0]
        return y
    return apply(fn, *args, _name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0, name=None):
    """LLM.int8() style linear (parity: paddle.nn.quant.llm_int8_linear).
    On TPU the mixed-decomposition trick (outlier columns in fp16) is
    subsumed by the fused dequant matmul above — implemented as the same
    computation, keeping the API for ported code."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale,
                              weight_dtype="int8")
