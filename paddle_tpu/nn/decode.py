"""Seq2seq decoding API (parity: python/paddle/nn/decode.py
BeamSearchDecoder / dynamic_decode).

TPU-native shape: the beam dimension is folded into the batch dimension
([B*K, ...]) so every step is one batched cell call; beam bookkeeping
(top-k over K*V, parent gather, finished freezing) is the same frozen-
beam algorithm as generation.GenerationMixin's beam search.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.creation import _coerce

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Wraps an RNN cell for beam-search decoding.

    embedding_fn maps token ids -> cell inputs; output_fn maps cell
    outputs -> vocabulary logits (both default to identity like the
    reference)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- decoder protocol (initialize / step), eager tensors -------------
    def initialize(self, initial_cell_states):
        states = initial_cell_states
        flat = states if isinstance(states, (list, tuple)) else [states]
        B = int(_coerce(flat[0])._value.shape[0])
        K = self.beam_size
        tiled = [Tensor(jnp.repeat(_coerce(s)._value, K, axis=0))
                 for s in flat]
        states = (tiled if isinstance(initial_cell_states, (list, tuple))
                  else tiled[0])
        ids = np.full((B * K,), self.start_token, np.int64)
        scores = np.full((B, K), -1e9, np.float32)
        scores[:, 0] = 0.0
        finished = np.zeros((B, K), bool)
        return ids, states, scores, finished

    def _embed(self, ids):
        t = Tensor(jnp.asarray(ids, jnp.int64))
        return self.embedding_fn(t) if self.embedding_fn is not None else t

    def step(self, inputs, states):
        out, next_states = self.cell(inputs, states)
        logits = self.output_fn(out) if self.output_fn is not None else out
        return logits, next_states


def dynamic_decode(decoder, inits=None, max_step_num=100, **kwargs):
    """Run decoder to completion (parity: paddle.nn.dynamic_decode).

    Returns (predicted_ids [B, T, beam], final_scores [B, beam]) —
    beams sorted best-first, positions after end_token filled with
    end_token (reference convention)."""
    K = decoder.beam_size
    end = decoder.end_token
    ids, states, scores, finished = decoder.initialize(inits)
    B = scores.shape[0]
    NEG = np.float32(-1e9)
    hist = []           # list of [B, K] int arrays
    parents = []

    def flat_states(ss):
        return ss if isinstance(ss, (list, tuple)) else [ss]

    for t in range(int(max_step_num)):
        inp = decoder._embed(ids)
        logits, states = decoder.step(inp, states)
        # log_softmax ON DEVICE, ONE download: the old path downloaded
        # the raw logits, re-uploaded them for log_softmax, then
        # downloaded again — three [B*K, V] transfers per step for one
        # (caught by graft-lint GL102)
        lv = _coerce(logits)._value.astype(jnp.float32)
        vocab = int(lv.shape[-1])
        # graft-lint: ok[GL102] — the designed per-step sync: beam
        # bookkeeping (top-k over K*V, parent gather) runs on host
        logp = np.asarray(jax.nn.log_softmax(lv, axis=-1))
        logp = logp.reshape(B, K, vocab)
        cont = scores[:, :, None] + logp
        frozen = np.full((B, K, vocab), NEG, np.float32)
        frozen[:, :, end] = scores
        cand = np.where(finished[:, :, None], frozen, cont)
        flat = cand.reshape(B, K * vocab)
        idx = np.argsort(-flat, axis=1)[:, :K]
        scores = np.take_along_axis(flat, idx, axis=1)
        parent = idx // vocab
        tok = (idx % vocab).astype(np.int64)
        # reorder states by parent beam
        gat = (np.arange(B)[:, None] * K + parent).reshape(-1)
        new_states = [Tensor(_coerce(s)._value[jnp.asarray(gat)])
                      for s in flat_states(states)]
        states = (new_states if isinstance(states, (list, tuple))
                  else new_states[0])
        finished = np.take_along_axis(finished, parent, axis=1)
        emit = np.where(finished, end, tok)
        hist.append(emit)
        parents.append(parent)
        finished |= tok == end
        ids = emit.reshape(-1)
        if finished.all():
            break

    # backtrack parent pointers into per-beam sequences
    T = len(hist)
    out = np.empty((B, T, K), np.int64)
    cur = np.tile(np.arange(K), (B, 1))
    for t in range(T - 1, -1, -1):
        out[:, t, :] = np.take_along_axis(hist[t], cur, axis=1)
        cur = np.take_along_axis(parents[t], cur, axis=1)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(scores))
