"""paddle.nn.utils parity (python/paddle/nn/utils/): weight
normalization hooks, gradient clipping utilities, parameter
flattening."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops._dispatch import apply
from ..ops.creation import _coerce

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def _norm_except(w, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(w * w))
    axes = tuple(a for a in range(w.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize layer.<name> as g * v / ||v|| (parity:
    python/paddle/nn/utils/weight_norm_hook.py). The recomputation runs
    in a forward-pre-hook, so the decomposition stays live under
    training."""
    w = getattr(layer, name)
    wv = w._value
    g0 = _norm_except(wv, dim)
    g = layer.create_parameter(list(np.shape(g0)) or [1])
    g.set_value(Tensor(jnp.reshape(g0, g._value.shape)))
    v = layer.create_parameter(list(wv.shape))
    v.set_value(Tensor(wv))
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the original weight becomes derived state, not a trainable param
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lyr, inputs):
        gv, vv = getattr(lyr, name + "_g"), getattr(lyr, name + "_v")
        new_w = apply(
            lambda gg, vx: (jnp.reshape(gg, _norm_except(vx, dim).shape)
                            * vx / (_norm_except(vx, dim) + 1e-12)),
            gv, vv)
        object.__setattr__(lyr, name, new_w)
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = (handle, name, dim)
    _recompute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a single parameter."""
    handle, nm, dim = getattr(layer, "_weight_norm_hook", (None, name, 0))
    if handle is not None:
        handle.remove()
    g = getattr(layer, nm + "_g")
    v = getattr(layer, nm + "_v")
    w = apply(lambda gg, vx: (jnp.reshape(gg, _norm_except(vx, dim).shape)
                              * vx / (_norm_except(vx, dim) + 1e-12)),
              g, v)
    p = layer.create_parameter(list(w._value.shape))
    p.set_value(w)
    layer.add_parameter(nm, p)
    del layer._parameters[nm + "_g"]
    del layer._parameters[nm + "_v"]
    if hasattr(layer, "_weight_norm_hook"):
        del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Parity: paddle.nn.utils.spectral_norm — wraps the layer's weight
    with a power-iteration spectral normalizer on each forward."""
    from .layers_common import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(list(w._value.shape), dim=dim,
             power_iters=n_power_iterations, epsilon=eps)
    orig = layer.create_parameter(list(w._value.shape))
    orig.set_value(Tensor(w._value))
    layer.add_parameter(name + "_orig", orig)
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lyr, inputs):
        object.__setattr__(lyr, name, sn(getattr(lyr, name + "_orig")))
        return None

    layer.register_forward_pre_hook(_recompute)
    _recompute(layer, None)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global-norm clip of .grad (parity:
    python/paddle/nn/utils/clip_grad_norm_.py). Returns the total norm."""
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._value)) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._value) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            "the total norm for gradients is non-finite; disable "
            "error_if_nonfinite to clip anyway")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad._value = p.grad._value * scale
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """In-place elementwise clip of .grad (parity: clip_grad_value_)."""
    cv = float(clip_value)
    for p in (parameters if isinstance(parameters, (list, tuple))
              else [parameters]):
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -cv, cv)


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one vector (parity:
    python/paddle/nn/utils/transform_parameters.py)."""
    return Tensor(jnp.concatenate(
        [jnp.ravel(_coerce(p)._value) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    """Write a flat vector back into the parameter tensors."""
    v = _coerce(vec)._value
    off = 0
    for p in parameters:
        n = int(np.prod(p._value.shape)) if p._value.shape else 1
        p.set_value(Tensor(jnp.reshape(v[off:off + n], p._value.shape)))
        off += n
    if off != v.shape[0]:
        raise ValueError(
            f"vector length {v.shape[0]} != total parameter size {off}")
