"""paddle.nn.functional — functional neural net ops.

Reference parity: python/paddle/nn/functional/*.py (activation, common,
conv, norm, loss, pooling, input). Conv/pool lower to
lax.conv_general_dilated / lax.reduce_window — XLA tiles these onto the
MXU; there is no cuDNN-style algorithm selection because XLA picks the
schedule at compile time (replaces paddle/phi/kernels/gpu/conv_kernel.cu).
"""
from __future__ import annotations

import functools
import math as pymath
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework import dtype as dtypes
from ..framework.random import next_key
from ..ops._dispatch import apply
from ..ops.creation import _coerce
from .._grad_mode import is_grad_enabled


# ------------------------------------------------------------ activations --
def relu(x, name=None):
    return apply(jax.nn.relu, _coerce(x), _name="relu")


def relu_(x, name=None):
    return x._inplace_update(relu(x))


def relu6(x, name=None):
    return apply(jax.nn.relu6, _coerce(x))


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, _coerce(x))


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, _coerce(x))


def tanh(x, name=None):
    return apply(jnp.tanh, _coerce(x))


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), _coerce(x),
                 _name="gelu")


def silu(x, name=None):
    return apply(jax.nn.silu, _coerce(x), _name="silu")


swish = silu


def mish(x, name=None):
    return apply(jax.nn.mish, _coerce(x))


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha=alpha), _coerce(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                 _coerce(x))


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha=alpha), _coerce(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope=negative_slope),
                 _coerce(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)
    return apply(fn, _coerce(x), _coerce(weight))


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    x = _coerce(x)
    if training:
        a = jax.random.uniform(next_key(), tuple(x._value.shape),
                               minval=lower, maxval=upper)
        return apply(lambda v: jnp.where(v >= 0, v, a.astype(v.dtype) * v), x)
    mid = (lower + upper) / 2.0
    return apply(lambda v: jnp.where(v >= 0, v, mid * v), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, value), _coerce(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda v: jnp.clip(v, min, max), _coerce(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), _coerce(x))


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(v > threshold, v - threshold,
                                     jnp.where(v < -threshold, v + threshold, 0.0)),
                 _coerce(x))


def tanhshrink(x, name=None):
    return apply(lambda v: v - jnp.tanh(v), _coerce(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), _coerce(x))


def hardswish(x, name=None):
    return apply(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, _coerce(x))


def softplus(x, beta=1, threshold=20, name=None):
    return apply(lambda v: jnp.where(beta * v > threshold, v,
                                     jnp.log1p(jnp.exp(beta * v)) / beta),
                 _coerce(x))


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, _coerce(x))


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        sh = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(sh), axis=ax + 1)
    return apply(fn, _coerce(x))


def glu(x, axis=-1, name=None):
    return apply(lambda v: jax.nn.glu(v, axis=axis), _coerce(x))


def softmax(x, axis=-1, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    def fn(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.softmax(v, axis=axis)
    return apply(fn, _coerce(x), _name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    def fn(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.log_softmax(v, axis=axis)
    return apply(fn, _coerce(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = _coerce(x)
    g = jax.random.gumbel(next_key(), tuple(x._value.shape))
    def fn(v):
        y = jax.nn.softmax((v + g.astype(v.dtype)) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y).at[
                tuple(jnp.indices(y.shape))[0:axis % y.ndim] + ()].set(0)
            onehot = jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis],
                                    axis=axis, dtype=y.dtype)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return apply(fn, x)


# ----------------------------------------------------------------- linear --
def linear(x, weight, bias=None, name=None):
    """paddle semantics: weight is [in_features, out_features] (NOT torch's
    transposed layout) — y = x @ W + b.
    Parity: python/paddle/nn/functional/common.py::linear →
    phi fc/matmul kernel."""
    if bias is None:
        return apply(lambda v, w: v @ w, _coerce(x), _coerce(weight),
                     _name="linear")
    return apply(lambda v, w, b: v @ w + b, _coerce(x), _coerce(weight),
                 _coerce(bias), _name="linear")


# ---------------------------------------------------------------- dropout --
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = _coerce(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda v: v * (1 - p), x)
        return x
    if p == 1.0:
        return apply(lambda v: jnp.zeros_like(v), x)
    shape = list(x._value.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = [s if i in [a % len(shape) for a in axes] else 1
                 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(shape))
    def fn(v):
        m = keep.astype(v.dtype)
        if mode == "upscale_in_train":
            return v * m / (1.0 - p)
        return v * m
    return apply(fn, x, _name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [2, 3] if data_format == "NCHW" else [1, 2]
    keep_axes = [i for i in range(4) if i not in ax]
    return dropout(x, p, axis=keep_axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [2, 3, 4] if data_format == "NCDHW" else [1, 2, 3]
    keep_axes = [i for i in range(5) if i not in ax]
    return dropout(x, p, axis=keep_axes, training=training)


def _alpha_dropout_impl(x, p, noise_shape):
    """Shared SELU-preserving dropout core: dropped entries are set to
    alpha' and the result is rescaled so a zero-mean unit-variance input
    keeps zero mean / unit variance (a = ((1-p)(1+p*alpha'^2))^-1/2)."""
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(next_key(), 1.0 - p, noise_shape)
    a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * p * alpha_p
    return apply(lambda v: a * jnp.where(keep, v, alpha_p) + b, x)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _coerce(x)
    if not training or p == 0.0:
        return x
    return _alpha_dropout_impl(x, p, tuple(x._value.shape))


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout that drops whole channels (axis 1) at once."""
    x = _coerce(x)
    if not training or p == 0.0:
        return x
    shape = list(x._value.shape)
    for i in range(2, len(shape)):
        shape[i] = 1
    return _alpha_dropout_impl(x, p, tuple(shape))


# ------------------------------------------------------------------- conv --
def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, ndim,
             channel_last, transpose=False, output_padding=0):
    n_sp = ndim
    stride = _pair(stride, n_sp)
    dilation = _pair(dilation, n_sp)

    if channel_last:
        # NHWC-style
        lhs_spec = "N" + "".join("DHW"[3 - n_sp + i] for i in range(n_sp)) + "C"
    else:
        lhs_spec = "NC" + "".join("DHW"[3 - n_sp + i] for i in range(n_sp))
    rhs_spec = "OI" + "".join("DHW"[3 - n_sp + i] for i in range(n_sp))
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        (1,) * (n_sp + 2), (1,) * (n_sp + 2), (lhs_spec, rhs_spec, out_spec))

    if isinstance(padding, str):
        pad = padding.upper()  # 'SAME' / 'VALID'
    elif isinstance(padding, (list, tuple)) and len(padding) == 2 * n_sp:
        pad = [(int(padding[2 * i]), int(padding[2 * i + 1]))
               for i in range(n_sp)]
    elif isinstance(padding, (list, tuple)) and len(padding) == n_sp and \
            isinstance(padding[0], (list, tuple)):
        pad = [tuple(int(q) for q in p) for p in padding]
    else:
        p = _pair(padding, n_sp)
        pad = [(i, i) for i in p]

    if not transpose:
        def fn(v, w, *b):
            out = jax.lax.conv_general_dilated(
                v, w, window_strides=stride, padding=pad,
                rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=v.dtype)
            if b:
                bias_shape = [1] * out.ndim
                bias_shape[dn.out_spec.index(1) if False else
                           (out.ndim - 1 if channel_last else 1)] = b[0].size
                out = out + b[0].reshape(bias_shape)
            return out
    else:
        opad = _pair(output_padding, n_sp)
        def fn(v, w, *b):
            # ConvTranspose = gradient of conv. paddle weight layout for
            # transpose conv: [in, out//groups, *k]
            if isinstance(pad, str):
                pd = pad
            else:
                # effective transpose padding: k-1-p on both sides + opad
                pd = []
                ks = w.shape[2:]
                for i in range(n_sp):
                    k_eff = (ks[i] - 1) * dilation[i]
                    lo = k_eff - pad[i][0]
                    hi = k_eff - pad[i][1] + opad[i]
                    pd.append((lo, hi))
            wt = jnp.swapaxes(w, 0, 1)  # [out//g, in, *k]
            if groups > 1:
                # regroup: weight [in, out//g, *k] → split on in
                wl = jnp.reshape(w, (groups, w.shape[0] // groups) + w.shape[1:])
                wt = jnp.concatenate([jnp.swapaxes(g_, 0, 1) for g_ in wl], axis=0)
            wt = jnp.flip(wt, axis=tuple(range(2, 2 + n_sp)))
            out = jax.lax.conv_general_dilated(
                v, wt, window_strides=(1,) * n_sp, padding=pd,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=groups,
                preferred_element_type=v.dtype)
            if b:
                bias_shape = [1] * out.ndim
                bias_shape[out.ndim - 1 if channel_last else 1] = b[0].size
                out = out + b[0].reshape(bias_shape)
            return out

    args = [_coerce(x), _coerce(weight)]
    if bias is not None:
        args.append(_coerce(bias))
    return apply(fn, *args, _name="conv")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=data_format == "NLC")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=data_format == "NHWC")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=data_format == "NDHWC")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=data_format == "NLC", transpose=True,
                    output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=data_format == "NHWC", transpose=True,
                    output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=data_format == "NDHWC", transpose=True,
                    output_padding=output_padding)


# ------------------------------------------------------------------ norm ---
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    args = [_coerce(x)]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_coerce(weight))
    if has_b:
        args.append(_coerce(bias))

    def fn(v, *wb):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        mu = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mu), axis=axes, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out
    return apply(fn, *args, _name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """TPU-native fused path exists in kernels.rms_norm; this is the lax
    fallback (XLA fuses it into one kernel anyway)."""
    args = [_coerce(x)]
    if weight is not None:
        args.append(_coerce(weight))
    def fn(v, *w):
        var = jnp.mean(jnp.square(v), axis=-1, keepdims=True)
        out = v * jax.lax.rsqrt(var + epsilon)
        return out * w[0] if w else out
    return apply(fn, *args, _name="rms_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    x = _coerce(x)
    ch_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    sh = [1] * x.ndim
    sh[ch_axis] = -1

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        # update running stats (stateful, paddle semantics: r = m*r + (1-m)*b)
        bm = apply(lambda v: jnp.mean(v, axis=reduce_axes), x)
        bv = apply(lambda v: jnp.var(v, axis=reduce_axes), x)
        if running_mean is not None:
            n = x.size // x._value.shape[ch_axis]
            unbiased = n / max(n - 1, 1)
            running_mean._value = (momentum * running_mean._value
                                   + (1 - momentum) * bm._value.astype(running_mean._value.dtype))
            running_var._value = (momentum * running_var._value
                                  + (1 - momentum) * (bv._value * unbiased).astype(running_var._value.dtype))
        mean_t, var_t = bm, bv
    else:
        mean_t, var_t = _coerce(running_mean), _coerce(running_var)

    args = [x, mean_t, var_t]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_coerce(weight))
    if has_b:
        args.append(_coerce(bias))

    def fn(v, mu, var, *wb):
        out = (v - mu.reshape(sh)) * jax.lax.rsqrt(var.reshape(sh) + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].reshape(sh)
            i += 1
        if has_b:
            out = out + wb[i].reshape(sh)
        return out
    return apply(fn, *args, _name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = _coerce(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    sh = [1] * x.ndim
    sh[ch_axis] = -1
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_coerce(weight))
    if has_b:
        args.append(_coerce(bias))
    def fn(v, *wb):
        mu = jnp.mean(v, axis=reduce_axes, keepdims=True)
        var = jnp.var(v, axis=reduce_axes, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        i = 0
        if has_w:
            out = out * wb[i].reshape(sh)
            i += 1
        if has_b:
            out = out + wb[i].reshape(sh)
        return out
    return apply(fn, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _coerce(x)
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_coerce(weight))
    if has_b:
        args.append(_coerce(bias))
    channel_last = not data_format.startswith("NC")
    def fn(v, *wb):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[0], v.shape[1]
        g = num_groups
        vv = v.reshape((n, g, c // g) + v.shape[2:])
        axes = tuple(range(2, vv.ndim))
        mu = jnp.mean(vv, axis=axes, keepdims=True)
        var = jnp.var(vv, axis=axes, keepdims=True)
        out = ((vv - mu) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        sh = [1] * out.ndim
        sh[1] = c
        i = 0
        if has_w:
            out = out * wb[i].reshape(sh)
            i += 1
        if has_b:
            out = out + wb[i].reshape(sh)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(fn, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(v):
        ch = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        pad_width = [(0, 0)] * v.ndim
        pad_width[ch] = (half, size - 1 - half)
        sq = jnp.pad(sq, pad_width)
        idx = [slice(None)] * v.ndim
        acc = jnp.zeros_like(v)
        for i in range(size):
            idx[ch] = slice(i, i + v.shape[ch])
            acc = acc + sq[tuple(idx)]
        # torch/paddle divide the window sum by `size` (both implement
        # LRN via zero-padded avg_pool — r5 fuzz find)
        return v / (k + alpha * acc / size) ** beta
    return apply(fn, _coerce(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply(lambda v: v / jnp.maximum(
        jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True), epsilon),
        _coerce(x))


# -------------------------------------------------------------- embedding --
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply(fn, _coerce(x), _coerce(weight), _name="embedding")


def one_hot(x, num_classes, name=None):
    return apply(lambda i: jax.nn.one_hot(i, num_classes,
                                          dtype=dtypes.get_default_dtype()),
                 _coerce(x))


# ---------------------------------------------------------------- pooling --
def _pool(x, op, init, kernel_size, stride, padding, ndim, channel_last,
          ceil_mode=False, exclusive=True, count_include_pad=False):
    ks = _pair(kernel_size, ndim)
    st = _pair(stride if stride is not None else kernel_size, ndim)
    pd = _pair(padding, ndim)

    def fn(v):
        sp_off = 1 if channel_last else 2
        if channel_last:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
        else:
            window = (1, 1) + ks
            strides = (1, 1) + st
        base = [(0, 0)] * v.ndim
        extra = [0] * v.ndim
        for i in range(ndim):
            d = sp_off + i
            base[d] = (pd[i], pd[i])
            out = _pool_out_size(v.shape[d], ks[i], st[i], pd[i],
                                 ceil_mode)
            extra[d] = max(0, (out - 1) * st[i] + ks[i]
                           - (v.shape[d] + 2 * pd[i]))
        pads = tuple((lo, hi + e) for (lo, hi), e in zip(base, extra))
        if op == "max":
            return jax.lax.reduce_window(v, -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min,
                                         jax.lax.max, window, strides, pads)
        s = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, pads)
        if exclusive and not count_include_pad:
            ones = jnp.ones_like(v)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pads)
            return s / cnt
        # include-pad divisor counts the window ∩ padded extent: the
        # base padding counts as cells, the ceil-mode overhang does not
        # (torch count_include_pad / paddle exclusive=False; r5 fuzz
        # find — dividing by k**n overcounted overhanging windows)
        ones_p = jnp.pad(jnp.ones_like(v), tuple(base),
                         constant_values=1.0)
        ext = tuple((0, e) for e in extra)
        cnt = jax.lax.reduce_window(ones_p, 0.0, jax.lax.add, window,
                                    strides, ext)
        return s / cnt
    return apply(fn, _coerce(x), _name=f"{op}_pool")


def _pool_out_size(n, k, s, p, ceil_mode):
    """Pooling output extent. ceil_mode allows a last partial window,
    but a window that would START in the right padding is skipped
    (torch/paddle rule; r5 fuzz find — naive ceil produced an extra
    output column for e.g. n=11, k=2, s=2, p=1)."""
    size = n + 2 * p
    if ceil_mode:
        out = -(-(size - k) // s) + 1
        if (out - 1) * s >= n + p:
            out -= 1
        return out
    return (size - k) // s + 1


def _max_pool_idx_raw(v, ks, st, pd, ceil_mode):
    """Variadic reduce_window over (value, flat-index) pairs; ties
    resolve to the first (row-major) position, matching the reference."""
    sp = v.shape[2:]
    ndim = len(sp)
    flat_n = 1
    for s in sp:
        flat_n *= s
    pos = jnp.arange(flat_n, dtype=jnp.int32).reshape(sp)
    pos = jnp.broadcast_to(pos, v.shape)
    window = (1, 1) + ks
    strides = (1, 1) + st
    pads = list(((0, 0), (0, 0)) + tuple((p, p) for p in pd))
    for i in range(ndim):
        d = 2 + i
        out = _pool_out_size(v.shape[d], ks[i], st[i], pd[i], ceil_mode)
        e = max(0, (out - 1) * st[i] + ks[i] - (v.shape[d] + 2 * pd[i]))
        lo, hi = pads[d]
        pads[d] = (lo, hi + e)
    pads = tuple(pads)
    neg = (-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
           else jnp.iinfo(v.dtype).min)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = (bv > av) | ((bv == av) & (bi < ai))
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    return jax.lax.reduce_window(
        (v, pos), (jnp.asarray(neg, v.dtype), jnp.asarray(flat_n,
                                                          jnp.int32)),
        sel, window, strides, pads)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _max_pool_idx(v, ks, st, pd, ceil_mode):
    out, idx = _max_pool_idx_raw(v, ks, st, pd, ceil_mode)
    return out, idx.astype(jnp.int64)


def _max_pool_idx_fwd(v, ks, st, pd, ceil_mode):
    out, idx = _max_pool_idx(v, ks, st, pd, ceil_mode)
    return (out, idx), (idx, v)


def _max_pool_idx_bwd(ks, st, pd, ceil_mode, res, g):
    # the max-pool gradient: route each output cotangent to its argmax
    # input position (indices themselves get no gradient)
    idx, v = res
    g_out = g[0].astype(jnp.float32)
    n, c = v.shape[0], v.shape[1]
    flat_n = 1
    for s in v.shape[2:]:
        flat_n *= s
    gi = idx.reshape(n, c, -1).astype(jnp.int32)
    gv = g_out.reshape(n, c, -1)
    dv = jax.vmap(jax.vmap(
        lambda i, val: jnp.zeros((flat_n,), jnp.float32).at[i].add(val)
    ))(gi, gv)
    return (dv.reshape(v.shape).astype(v.dtype),)


_max_pool_idx.defvjp(_max_pool_idx_fwd, _max_pool_idx_bwd)


def _max_pool_with_mask(x, kernel_size, stride, padding, ndim, ceil_mode):
    """Max pool that also returns the flat argmax index within each
    input spatial plane (paddle return_mask semantics; reference:
    phi max_pool2d_with_index kernel)."""
    ks = _pair(kernel_size, ndim)
    st = _pair(stride if stride is not None else kernel_size, ndim)
    pd = _pair(padding, ndim)
    return apply(lambda v: _max_pool_idx(v, ks, st, pd, ceil_mode),
                 _coerce(x), _name="max_pool_mask")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        if data_format != "NCL":  # same restriction as the reference
            raise ValueError("return_mask requires NCL data_format")
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   ceil_mode)
    return _pool(x, "max", None, kernel_size, stride, padding, 1,
                 data_format == "NLC", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":  # same restriction as the reference
            raise ValueError("return_mask requires NCHW data_format")
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   ceil_mode)
    return _pool(x, "max", None, kernel_size, stride, padding, 2,
                 data_format == "NHWC", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if data_format != "NCDHW":  # same restriction as the reference
            raise ValueError("return_mask requires NCDHW data_format")
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   ceil_mode)
    return _pool(x, "max", None, kernel_size, stride, padding, 3,
                 data_format == "NDHWC", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, "avg", None, kernel_size, stride, padding, 1,
                 data_format == "NLC", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, "avg", None, kernel_size, stride, padding, 2,
                 data_format == "NHWC", ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, "avg", None, kernel_size, stride, padding, 3,
                 data_format == "NDHWC", ceil_mode, exclusive)


def _lp_pool(x, norm_type, kernel_size, stride, padding, ndim,
             ceil_mode, channel_last, avg_fn, fmt):
    """(sum over window of x^p)^(1/p); p=inf degenerates to max pool.
    Composed as inclusive-avg-pool of x^p scaled by the window size
    (zero padding contributes 0 to the sum). NOTE reference semantics:
    no abs — negative inputs with odd/fractional p produce NaN exactly
    as the reference implementation does."""
    p = float(norm_type)
    if p == float("inf"):
        return _pool(x, "max", None, kernel_size, stride, padding, ndim,
                     channel_last, ceil_mode)
    if p <= 0:  # note: rejects -inf too
        raise ValueError("lp_pool norm_type must be positive")
    ks = _pair(kernel_size, ndim)
    win = 1
    for k in ks:
        win *= k
    xp = _coerce(x) ** p
    s = avg_fn(xp, kernel_size, stride, padding, ceil_mode=ceil_mode,
               exclusive=False, data_format=fmt) * float(win)
    return s ** (1.0 / p)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Power-average pooling (parity: paddle.nn.functional.lp_pool1d)."""
    def a1(v, k, s_, pad, ceil_mode, exclusive, data_format):
        return avg_pool1d(v, k, s_, pad, exclusive=exclusive,
                          ceil_mode=ceil_mode, data_format=data_format)
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 1,
                    ceil_mode, data_format == "NLC", a1, data_format)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """Power-average pooling (parity: paddle.nn.functional.lp_pool2d)."""
    def a2(v, k, s_, pad, ceil_mode, exclusive, data_format):
        return avg_pool2d(v, k, s_, pad, ceil_mode=ceil_mode,
                          exclusive=exclusive, data_format=data_format)
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 2,
                    ceil_mode, data_format == "NHWC", a2, data_format)


def _adaptive_pool(x, output_size, ndim, op, channel_last):
    x = _coerce(x)
    out_sz = _pair(output_size, ndim)
    sp_off = 1 if channel_last else 2

    def fn(v):
        out = v
        for i in range(ndim):
            d = sp_off + i
            in_s = out.shape[d]
            o = out_sz[i] if out_sz[i] is not None else in_s
            if in_s % o == 0:
                k = in_s // o
                sh = out.shape[:d] + (o, k) + out.shape[d + 1:]
                r = out.reshape(sh)
                out = jnp.max(r, axis=d + 1) if op == "max" else jnp.mean(r, axis=d + 1)
            else:
                # general adaptive: per-output-bin reduce
                starts = (np.arange(o) * in_s) // o
                ends = ((np.arange(o) + 1) * in_s + o - 1) // o
                segs = [jnp.max(jnp.take(out, np.arange(s, e), axis=d), axis=d)
                        if op == "max" else
                        jnp.mean(jnp.take(out, np.arange(s, e), axis=d), axis=d)
                        for s, e in zip(starts, ends)]
                out = jnp.stack(segs, axis=d)
        return out
    return apply(fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "max", False)


# ------------------------------------------------------------------ loss ---
def _reduce_loss(loss, reduction):
    from ..ops import math as m
    if reduction == "mean":
        return m.mean(loss)
    if reduction == "sum":
        return m.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Parity: python/paddle/nn/functional/loss.py::cross_entropy
    (softmax_with_cross_entropy kernel)."""
    args = [_coerce(input), _coerce(label)]
    has_w = weight is not None
    if has_w:
        args.append(_coerce(weight))

    def fn(logits, lab, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label or (lab.ndim == logits.ndim and
                          jnp.issubdtype(lab.dtype, jnp.floating)):
            tgt = lab
            if label_smoothing > 0.0:
                n = logits.shape[axis]
                tgt = tgt * (1 - label_smoothing) + label_smoothing / n
            nll = -jnp.sum(tgt * logp, axis=axis)
            if has_w:
                nll = nll * jnp.sum(tgt * w[0], axis=axis)
            return nll
        lab_i = lab
        if lab_i.ndim == logits.ndim:
            lab_i = jnp.squeeze(lab_i, axis=axis)
        n = logits.shape[axis]
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        oh = jax.nn.one_hot(safe, n, axis=axis, dtype=logp.dtype)
        if label_smoothing > 0.0:
            oh = oh * (1 - label_smoothing) + label_smoothing / n
        nll = -jnp.sum(oh * logp, axis=axis)
        if has_w:
            # paddle smears the class weight over the SMOOTHED target
            # distribution (loss.py: weight_gather = q @ w), not just
            # the hard label (r5 fuzz find):
            #   w_i = (1-ls)·w[y_i] + (ls/n)·Σ_c w_c
            wi = jnp.take(w[0], safe)
            if label_smoothing > 0.0:
                wi = ((1 - label_smoothing) * wi
                      + (label_smoothing / n) * jnp.sum(w[0]))
            nll = nll * wi
        # an out-of-range label (not ignore_index) must surface loudly:
        # jax one_hot silently yields an all-zero row and a 0.0 loss
        # (the upstream kernel PADDLE_ENFORCEs label < C; r5 find)
        oob = valid & ((lab_i < 0) | (lab_i >= n))
        nll = jnp.where(oob, jnp.nan, nll)
        return jnp.where(valid, nll, 0.0)

    loss = apply(fn, *args, _name="cross_entropy")
    if reduction == "mean":
        lab = args[1]
        in_ndim = args[0].ndim
        if not soft_label and jnp.issubdtype(lab._value.dtype, jnp.integer):
            # mean over non-ignored entries (paddle semantics); weighted mean
            # divides by the sum of per-sample weights
            def mean_fn(l, labd, *w):
                li = jnp.squeeze(labd, axis=axis) if labd.ndim == in_ndim else labd
                valid = li != ignore_index
                if has_w:
                    safe = jnp.where(valid, li, 0)
                    wi = jnp.take(w[0], safe)
                    if label_smoothing > 0.0:
                        # denominator uses the same smeared weights as
                        # the numerator (paddle: sum(weight_gather))
                        n = int(w[0].shape[0])
                        wi = ((1 - label_smoothing) * wi
                              + (label_smoothing / n) * jnp.sum(w[0]))
                    den = jnp.sum(jnp.where(valid, wi, 0.0))
                else:
                    den = jnp.sum(valid.astype(l.dtype))
                # the guard only protects the all-ignored case (0/0 → 0);
                # clamping to 1.0 corrupted weighted means whose weight
                # sum is < 1 (r5 fuzz find)
                return jnp.sum(l) / jnp.maximum(den, 1e-12)
            return apply(mean_fn, loss, lab, *args[2:])
        return _reduce_loss(loss, "mean")
    return _reduce_loss(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from ..ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    args = [_coerce(input), _coerce(label)]
    has_w = weight is not None
    if has_w:
        args.append(_coerce(weight))
    def fn(logp, lab, *w):
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        if logp.ndim == lab.ndim + 1:
            # class dim is axis 1 for any rank — (N,C), (N,C,d1,d2,...);
            # the index must be expanded THERE, not at the last axis
            # (spatial nll was picking along W — r4 fuzz find)
            cls_axis = 1 if logp.ndim > 1 else 0
            picked = -jnp.take_along_axis(
                logp, jnp.expand_dims(safe, cls_axis), axis=cls_axis)
            picked = jnp.squeeze(picked, axis=cls_axis)
        else:
            picked = -jnp.take_along_axis(logp, safe,
                                          axis=1 if logp.ndim > 1 else 0)
        if has_w:
            picked = picked * jnp.take(w[0], safe)
        return jnp.where(valid, picked, 0.0)
    loss = apply(fn, *args)
    if reduction == "mean" and has_w:
        def den_fn(l, lab, w):
            valid = lab != ignore_index
            safe = jnp.where(valid, lab, 0)
            return jnp.sum(l) / jnp.sum(jnp.where(valid, jnp.take(w, safe), 0.0))
        return apply(den_fn, loss, args[1], args[2])
    if reduction == "mean":
        # mean over NON-IGNORED entries (torch/paddle denominator), not
        # the total element count (review r4 find)
        def mean_fn(l, lab):
            valid = lab != ignore_index
            return jnp.sum(l) / jnp.maximum(jnp.sum(valid), 1)
        return apply(mean_fn, loss, args[1])
    return _reduce_loss(loss, reduction)


def mse_loss(input, label, reduction="mean", name=None):
    loss = apply(lambda a, b: jnp.square(a - b), _coerce(input), _coerce(label))
    return _reduce_loss(loss, reduction)


def l1_loss(input, label, reduction="mean", name=None):
    loss = apply(lambda a, b: jnp.abs(a - b), _coerce(input), _coerce(label))
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def huber(a, b):
        d = a - b
        ad = jnp.abs(d)
        return jnp.where(ad < delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    loss = apply(huber, _coerce(input), _coerce(label))
    return _reduce_loss(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = [_coerce(input), _coerce(label)]
    has_w = weight is not None
    if has_w:
        args.append(_coerce(weight))
    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        out = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        return out * w[0] if has_w else out
    loss = apply(fn, *args)
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    args = [_coerce(logit), _coerce(label)]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        args.append(_coerce(weight))
    if has_pw:
        args.append(_coerce(pos_weight))
    def fn(z, y, *rest):
        # stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if has_pw:
            pw = rest[-1]
            logsig = -jax.nn.softplus(-z)
            log1msig = -z - jax.nn.softplus(-z)
            base = -(pw * y * logsig + (1 - y) * log1msig)
        if has_w:
            base = base * rest[0]
        return base
    loss = apply(fn, *args)
    return _reduce_loss(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, t):
        if log_target:
            return jnp.exp(t) * (t - lp)
        return jnp.where(t > 0, t * (jnp.log(t) - lp), 0.0)
    loss = apply(fn, _coerce(input), _coerce(label))
    if reduction == "batchmean":
        from ..ops import math as m
        n = _coerce(input)._value.shape[0]
        return m.divide(m.sum(loss), float(n))
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    loss = apply(lambda a, b, y: jnp.maximum(0.0, -y * (a - b) + margin),
                 _coerce(input), _coerce(other), _coerce(label))
    return _reduce_loss(loss, reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.maximum(jnp.linalg.norm(a, axis=axis) *
                          jnp.linalg.norm(b, axis=axis), eps)
        return num / den
    return apply(fn, _coerce(x1), _coerce(x2))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    loss = apply(fn, _coerce(input1), _coerce(input2), _coerce(label))
    return _reduce_loss(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return jnp.maximum(dp - dn + margin, 0.0)
    loss = apply(fn, _coerce(input), _coerce(positive), _coerce(negative))
    return _reduce_loss(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(x, y):
        return jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    loss = apply(fn, _coerce(input), _coerce(label))
    return _reduce_loss(loss, reduction)


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), _coerce(input), _coerce(label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss (parity:
    python/paddle/nn/functional/loss.py ctc_loss; upstream
    phi/kernels/gpu/warpctc_kernel.cu binds warp-ctc). TPU-native: the
    log-domain forward algorithm as a lax.scan over time — one compiled
    recurrence instead of a CUDA kernel; alpha lives in registers/VMEM
    and the whole thing fuses under jit.

    log_probs: [T, B, C] (time-major, already log-softmaxed);
    labels: [B, L] int; input_lengths/label_lengths: [B] int."""
    def fn(lp, lab, in_len, lab_len):
        t_max, b, c = lp.shape
        l_max = lab.shape[1]
        s = 2 * l_max + 1  # extended label: blank l1 blank l2 ... blank
        lab = lab.astype(jnp.int32)
        ext = jnp.full((b, s), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        # transition mask: from s-2 allowed iff ext[s] != blank and
        # ext[s] != ext[s-2]
        ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s]
        allow_skip = (ext != blank) & (ext != ext_m2)
        pos = jnp.arange(s)
        # emission log-prob of extended symbol j at time t
        def emit(lp_t):
            return jnp.take_along_axis(lp_t, ext, axis=1)  # [B, S]

        alpha0 = jnp.full((b, s), neg_inf, lp.dtype)
        alpha0 = alpha0.at[:, 0].set(emit(lp[0])[:, 0])
        has1 = (s > 1)
        if has1:
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(lab_len > 0, emit(lp[0])[:, 1], neg_inf))

        def step(alpha, lp_t):
            e = emit(lp_t)
            a_prev = jnp.pad(alpha, ((0, 0), (1, 0)),
                             constant_values=-1e30)[:, :s]
            a_skip = jnp.pad(alpha, ((0, 0), (2, 0)),
                             constant_values=-1e30)[:, :s]
            a_skip = jnp.where(allow_skip, a_skip, neg_inf)
            stacked = jnp.stack([alpha, a_prev, a_skip], axis=0)
            new = jax.nn.logsumexp(stacked, axis=0) + e
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,S]
        # per-sample final: alpha[T_b - 1, 2*L_b] lse alpha[T_b - 1, 2*L_b - 1]
        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, t_max - 1)
        a_final = jnp.take_along_axis(
            alphas, t_idx[None, :, None].repeat(s, axis=2), axis=0)[0]
        end0 = 2 * lab_len.astype(jnp.int32)
        end1 = jnp.maximum(end0 - 1, 0)
        f0 = jnp.take_along_axis(a_final, end0[:, None], axis=1)[:, 0]
        f1 = jnp.take_along_axis(a_final, end1[:, None], axis=1)[:, 0]
        f1 = jnp.where(lab_len > 0, f1, neg_inf)
        loss = -jax.nn.logsumexp(jnp.stack([f0, f1]), axis=0)
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(loss.dtype), 1.0)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(
                lab_len.astype(loss.dtype), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, _coerce(log_probs), _coerce(labels),
                 _coerce(input_lengths), _coerce(label_lengths))


# ------------------------------------------------------------- attention ---
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity
    (python/paddle/nn/functional/flash_attention.py). Layout: [B, S, H, D]
    (paddle flash-attention layout). Uses the Pallas flash kernel on TPU
    when available, else the XLA softmax path."""
    from ..kernels.attention import flash_attention_bshd
    return flash_attention_bshd(query, key, value, attn_mask=attn_mask,
                                dropout_p=dropout_p, is_causal=is_causal,
                                training=training)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity.
    Layout [B, S, H, D]; returns (out, softmax) — softmax is None unless
    return_softmax (the reference only materializes it for debugging;
    here that falls back to the XLA path to keep the kernel online-only).
    """
    if return_softmax:
        # debug path: materializes the softmax, so it cannot use the
        # online Pallas kernel — plain XLA attention with the same math
        q, k, v = (_coerce(t) for t in (query, key, value))
        drop_key = (next_key() if dropout > 0.0 and training else None)

        def fn(qv, kv, vv):
            qt = jnp.swapaxes(qv, 1, 2)
            kt = jnp.swapaxes(kv, 1, 2)
            vt = jnp.swapaxes(vv, 1, 2)
            scale = qt.shape[-1] ** -0.5
            s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
            if causal:
                qlen, klen = s.shape[-2], s.shape[-1]
                mask = jnp.tril(jnp.ones((qlen, klen), bool))
                s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s, axis=-1)
            if drop_key is not None:
                keep = jax.random.bernoulli(drop_key, 1.0 - dropout,
                                            p.shape)
                p = jnp.where(keep, p / (1.0 - dropout), 0.0)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
            return jnp.swapaxes(o, 1, 2), p
        return apply(fn, q, k, v, _name="flash_attention")
    out = scaled_dot_product_attention(query, key, value, attn_mask=None,
                                       dropout_p=dropout, is_causal=causal,
                                       training=training)
    return out, None


# ------------------------------------------------------------------ misc ---
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = _coerce(x)
    nd = x.ndim - 2
    channel_last = not data_format.startswith("NC")
    sp_off = 1 if channel_last else 2
    in_sizes = [x._value.shape[sp_off + i] for i in range(nd)]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    scales = None
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
        out_sizes = [int(in_sizes[i] * float(sf[i])) for i in range(nd)]
        # the kernels map coordinates with the EXACT scale when one was
        # given (paddle: ratio = 1/scale), not the derived size ratio —
        # they differ for fractional factors (r5 fuzz find)
        scales = [float(s) for s in sf]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def _cubic_weights(frac):
        """Keys cubic-convolution weights for the 4 taps around a
        sample, a = -0.75 (torch/paddle kernel; jax.image's cubic uses
        a = -0.5, which diverges ~1e-1 — r4 fuzz find). frac: [O] in
        [0,1). Returns [O, 4]."""
        a = -0.75
        d = jnp.stack([frac + 1.0, frac, 1.0 - frac, 2.0 - frac], axis=-1)
        w_near = (a + 2.0) * d ** 3 - (a + 3.0) * d ** 2 + 1.0      # |d|<=1
        w_far = a * d ** 3 - 5.0 * a * d ** 2 + 8.0 * a * d - 4.0 * a
        return jnp.where(d <= 1.0, w_near, w_far)

    def _cubic_1d(v, axis, out_len):
        """Separable bicubic resample of `v` along `axis` (half-pixel
        or align_corners mapping), border-replicated taps."""
        s = v.shape[axis]
        if align_corners:
            # o == 1: torch/paddle sample index 0 (not the half-pixel
            # center) under align_corners
            src = (jnp.zeros((1,), jnp.float32) if out_len == 1 else
                   jnp.arange(out_len, dtype=jnp.float32) *
                   ((s - 1) / (out_len - 1)))
        else:
            sc = scales[axis - sp_off] if scales is not None else None
            scale_ = (1.0 / sc) if sc else (s / out_len)
            src = (jnp.arange(out_len, dtype=jnp.float32) + 0.5) * \
                scale_ - 0.5
        base = jnp.floor(src)
        frac = src - base
        w = _cubic_weights(frac)                       # [O, 4]
        idx = base[:, None].astype(jnp.int32) + \
            jnp.arange(-1, 3, dtype=jnp.int32)[None]   # [O, 4]
        idx = jnp.clip(idx, 0, s - 1)
        taps = jnp.take(v, idx.reshape(-1), axis=axis)
        new_shape = (v.shape[:axis] + (out_len, 4)
                     + v.shape[axis + 1:])
        taps = taps.reshape(new_shape)
        wshape = [1] * len(new_shape)
        wshape[axis], wshape[axis + 1] = out_len, 4
        # weights are f32; keep the input dtype (bf16 pipelines must not
        # silently upcast — every other interpolate mode preserves dtype)
        return jnp.sum(taps.astype(jnp.float32) * w.reshape(wshape),
                       axis=axis + 1).astype(v.dtype)

    def _nearest_1d(v, axis, out_len):
        """torch/paddle nearest mapping: src = floor(dst·in/out)
        (align_corners: round(dst·(in-1)/(out-1))). jax.image.resize's
        half-pixel-rounded nearest picked different source rows on
        downscale — r5 fuzz find."""
        s = v.shape[axis]
        o = np.arange(out_len)
        sc = scales[axis - sp_off] if scales is not None else None
        if align_corners:
            idx = (np.zeros(1) if out_len == 1
                   else np.round(o * ((s - 1) / (out_len - 1))))
        else:
            ratio = (1.0 / sc) if sc else (s / out_len)
            idx = np.floor(o * ratio)
        idx = np.clip(idx.astype(np.int32), 0, s - 1)
        return jnp.take(v, jnp.asarray(idx), axis=axis)

    def _area_1d(v, axis, out_len):
        """'area' is adaptive average pooling (torch/paddle): cell o
        averages rows floor(o·in/out) .. ceil((o+1)·in/out); separable
        per axis. Windowed segment means (gather the ≤wmax taps of each
        cell and weight them directly) rather than a full-axis float32
        cumsum difference: the cumsum grows with the axis so for long
        axes the subtraction cancels most significant bits and each
        cell's mean loses precision proportionally to its position —
        ADVICE r5 #3. Window math keeps every cell's error independent
        of axis length."""
        s = v.shape[axis]
        o = np.arange(out_len, dtype=np.int64)
        starts = o * s // out_len
        ends = -(-(o + 1) * s // out_len)
        wmax = int((ends - starts).max())
        idx = starts[:, None] + np.arange(wmax, dtype=np.int64)[None, :]
        valid = idx < ends[:, None]
        idx = np.minimum(idx, s - 1)
        cnt = (ends - starts).astype(np.float32)
        w = valid.astype(np.float32) / cnt[:, None]
        taps = jnp.take(v, jnp.asarray(idx.reshape(-1)), axis=axis)
        new_shape = v.shape[:axis] + (out_len, wmax) + v.shape[axis + 1:]
        taps = taps.reshape(new_shape)
        wshape = [1] * len(new_shape)
        wshape[axis], wshape[axis + 1] = out_len, wmax
        return jnp.sum(taps.astype(jnp.float32)
                       * jnp.asarray(w).reshape(wshape),
                       axis=axis + 1).astype(v.dtype)

    def fn(v):
        shape = list(v.shape)
        for i in range(nd):
            shape[sp_off + i] = out_sizes[i]
        if jmode == "nearest":
            out = v
            for i in range(nd):
                out = _nearest_1d(out, sp_off + i, out_sizes[i])
            return out
        if mode == "area":
            out = v
            for i in range(nd):
                out = _area_1d(out, sp_off + i, out_sizes[i])
            return out
        if jmode == "cubic":
            out = v
            for i in range(nd):
                out = _cubic_1d(out, sp_off + i, out_sizes[i])
            return out
        if align_corners:
            # jax.image.resize uses half-pixel centers; emulate align_corners
            # via explicit coordinate map with map_coordinates
            coords = []
            for i in range(nd):
                o = out_sizes[i]
                s = in_sizes[i]
                if o == 1:
                    c = jnp.zeros((1,))
                else:
                    c = jnp.linspace(0, s - 1, o)
                coords.append(c)
            # build full grid over spatial dims only; vmap over N,C
            grid = jnp.meshgrid(*coords, indexing="ij")
            def sample(img):
                return jax.scipy.ndimage.map_coordinates(img, grid, order=1)
            bat = v if not channel_last else jnp.moveaxis(v, -1, 1)
            out = jax.vmap(jax.vmap(sample))(bat)
            return out if not channel_last else jnp.moveaxis(out, 1, -1)
        # antialias=False: torch/paddle linear interpolation does not
        # low-pass filter on downscale (jax.image.resize's default
        # antialias=True diverged there — r5 fuzz find)
        return jax.image.resize(v, shape, method=jmode, antialias=False)
    return apply(fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply(fn, _coerce(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 2, 4, 1, 3, 5).reshape(n, h // r, w // r, c * r * r)
        return v
    return apply(fn, _coerce(x))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _pair(kernel_sizes, 2)
    st = _pair(strides, 2)
    pd = _pair(paddings, 2)
    dl = _pair(dilations, 2)
    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        oh = (h + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (w + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        patches = []
        for ki in range(ks[0]):
            for kj in range(ks[1]):
                sub = v[:, :, ki * dl[0]: ki * dl[0] + oh * st[0]: st[0],
                        kj * dl[1]: kj * dl[1] + ow * st[1]: st[1]]
                patches.append(sub)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply(fn, _coerce(x))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..ops.manipulation import pad as _pad_op
    return _pad_op(x, pad, mode, value, data_format)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        n = l.shape[-1]
        return l * (1 - epsilon) + epsilon / n
    return apply(fn, _coerce(label))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = _coerce(x)
    ml = int(maxlen) if maxlen is not None else int(np.asarray(x._value).max())
    d = dtypes.convert_dtype(dtype)
    return apply(lambda v: (jnp.arange(ml) < v[..., None]).astype(d), x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)
    return apply(fn, _coerce(x))


# second-tier surface (spatial transformer ops, unpooling, loss long
# tail) lives in functional_extra to keep this module navigable
from .functional_extra import *  # noqa: F401,F403,E402


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    """Alias re-export (parity: paddle.nn.functional.diag_embed)."""
    from ..ops.creation import diag_embed as _de
    return _de(x, offset=offset, dim1=dim1, dim2=dim2)
