"""RNN family (parity: python/paddle/nn/layer/rnn.py: SimpleRNN, LSTM, GRU,
plus the cell classes and RNN wrapper).

TPU-native design: the whole time loop is one `lax.scan` inside a single
tape op — XLA compiles the recurrence into one fused loop; there is no
per-timestep python dispatch (replaces paddle's cudnn RNN descriptors in
paddle/phi/kernels/gpu/rnn_kernel.cu with a compiler-scheduled scan).
"""
from __future__ import annotations

import math as pymath

import jax
import jax.numpy as jnp

from .layer_base import Layer
from .initializer import Uniform
from ..ops._dispatch import apply
from ..ops.creation import _coerce


def _cell_step(mode, x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    if mode == "GRU":
        # paddle GRU: candidate gate applies r to (W_hh_n h + b_hh_n)
        gates_x = x_t @ w_ih.T + (b_ih if b_ih is not None else 0.0)
        gates_h = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        rx, zx, nx = jnp.split(gates_x, 3, axis=-1)
        rh, zh, nh = jnp.split(gates_h, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        h_new = (1 - z) * n + z * h
        return h_new, None
    gates = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    # SimpleRNN (tanh / relu)
    act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
    h_new = act(gates)
    return h_new, None


class RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        g = {"LSTM": 4, "GRU": 3}.get(mode, 1)
        k = 1.0 / pymath.sqrt(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                sfx = f"_reverse" if d == 1 else ""
                w_ih = self.create_parameter(
                    [g * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=Uniform(-k, k))
                w_hh = self.create_parameter(
                    [g * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=Uniform(-k, k))
                b_ih = self.create_parameter(
                    [g * hidden_size], attr=bias_ih_attr, is_bias=True,
                    default_initializer=Uniform(-k, k))
                b_hh = self.create_parameter(
                    [g * hidden_size], attr=bias_hh_attr, is_bias=True,
                    default_initializer=Uniform(-k, k))
                self.add_parameter(f"weight_ih_l{layer}{sfx}", w_ih)
                self.add_parameter(f"weight_hh_l{layer}{sfx}", w_hh)
                self.add_parameter(f"bias_ih_l{layer}{sfx}", b_ih)
                self.add_parameter(f"bias_hh_l{layer}{sfx}", b_hh)
                self._all_weights.append(
                    (f"weight_ih_l{layer}{sfx}", f"weight_hh_l{layer}{sfx}",
                     f"bias_ih_l{layer}{sfx}", f"bias_hh_l{layer}{sfx}"))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = _coerce(inputs)
        mode = self.mode
        num_dirs = 2 if self.bidirect else 1
        nl = self.num_layers
        hs = self.hidden_size
        time_major = self.time_major
        is_lstm = mode == "LSTM"

        weights = []
        for names in self._all_weights:
            weights.extend(self._parameters[n] for n in names)

        bshape_known = x._value.shape[1 if time_major else 0]

        has_init = initial_states is not None
        init_args = []
        if has_init:
            if is_lstm:
                h0, c0 = initial_states
                init_args = [_coerce(h0), _coerce(c0)]
            else:
                init_args = [_coerce(initial_states)]

        def fn(xv, *flat):
            ws = flat[:len(weights)]
            rest = flat[len(weights):]
            if time_major:
                xv = jnp.swapaxes(xv, 0, 1)  # → [B, T, F]
            b = xv.shape[0]
            if rest:
                h0 = rest[0]
                c0 = rest[1] if is_lstm else None
            else:
                h0 = jnp.zeros((nl * num_dirs, b, hs), xv.dtype)
                c0 = jnp.zeros((nl * num_dirs, b, hs), xv.dtype) if is_lstm else None

            out = xv
            h_finals, c_finals = [], []
            wi = 0
            for layer in range(nl):
                dir_outs = []
                for d in range(num_dirs):
                    w_ih, w_hh, b_ih, b_hh = ws[wi * 4: wi * 4 + 4]
                    idx = layer * num_dirs + d
                    hh = h0[idx]
                    cc = c0[idx] if is_lstm else jnp.zeros_like(hh)
                    seq = out if d == 0 else jnp.flip(out, axis=1)
                    xs = jnp.swapaxes(seq, 0, 1)  # [T, B, F]

                    def step(carry, x_t):
                        h, c = carry
                        h2, c2 = _cell_step(mode, x_t, h, c, w_ih, w_hh,
                                            b_ih, b_hh)
                        return (h2, c2 if c2 is not None else c), h2

                    (hT, cT), ys = jax.lax.scan(step, (hh, cc), xs)
                    ys = jnp.swapaxes(ys, 0, 1)  # [B, T, H]
                    if d == 1:
                        ys = jnp.flip(ys, axis=1)
                    dir_outs.append(ys)
                    h_finals.append(hT)
                    c_finals.append(cT)
                    wi += 1
                out = dir_outs[0] if num_dirs == 1 else jnp.concatenate(
                    dir_outs, axis=-1)
            h_all = jnp.stack(h_finals, axis=0)
            outputs = jnp.swapaxes(out, 0, 1) if time_major else out
            if is_lstm:
                return outputs, h_all, jnp.stack(c_finals, axis=0)
            return outputs, h_all

        res = apply(fn, x, *weights, *init_args, _name=mode.lower())
        if is_lstm:
            outputs, h, c = res
            return outputs, (h, c)
        outputs, h = res
        return outputs, h


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        k = 1.0 / pymath.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=Uniform(-k, k))
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=Uniform(-k, k))
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=Uniform(-k, k))
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=Uniform(-k, k))

    def forward(self, inputs, states=None):
        x = _coerce(inputs)
        if states is None:
            from ..ops.creation import zeros
            b = x.shape[0]
            states = (zeros([b, self.hidden_size], dtype=str(x.dtype)),
                      zeros([b, self.hidden_size], dtype=str(x.dtype)))
        h, c = states
        def fn(xv, hv, cv, wi, wh, bi, bh):
            return _cell_step("LSTM", xv, hv, cv, wi, wh, bi, bh)
        h2, c2 = apply(fn, x, _coerce(h), _coerce(c), self.weight_ih,
                       self.weight_hh, self.bias_ih, self.bias_hh)
        return h2, (h2, c2)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        k = 1.0 / pymath.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], default_initializer=Uniform(-k, k))
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], default_initializer=Uniform(-k, k))
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], is_bias=True, default_initializer=Uniform(-k, k))
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], is_bias=True, default_initializer=Uniform(-k, k))

    def forward(self, inputs, states=None):
        x = _coerce(inputs)
        if states is None:
            from ..ops.creation import zeros
            states = zeros([x.shape[0], self.hidden_size], dtype=str(x.dtype))
        def fn(xv, hv, wi, wh, bi, bh):
            h2, _ = _cell_step("GRU", xv, hv, None, wi, wh, bi, bh)
            return h2
        h2 = apply(fn, x, _coerce(states), self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh)
        return h2, h2


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__()
        self.hidden_size = hidden_size
        self.mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        k = 1.0 / pymath.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], default_initializer=Uniform(-k, k))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], default_initializer=Uniform(-k, k))
        self.bias_ih = self.create_parameter(
            [hidden_size], is_bias=True, default_initializer=Uniform(-k, k))
        self.bias_hh = self.create_parameter(
            [hidden_size], is_bias=True, default_initializer=Uniform(-k, k))

    def forward(self, inputs, states=None):
        x = _coerce(inputs)
        if states is None:
            from ..ops.creation import zeros
            states = zeros([x.shape[0], self.hidden_size], dtype=str(x.dtype))
        def fn(xv, hv, wi, wh, bi, bh):
            h2, _ = _cell_step(self.mode, xv, hv, None, wi, wh, bi, bh)
            return h2
        h2 = apply(fn, x, _coerce(states), self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh)
        return h2, h2


class RNNCellBase(Layer):
    """Base for user-defined recurrent cells (parity: python/paddle/nn/
    layer/rnn.py RNNCellBase): provides get_initial_states for the RNN
    wrapper."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ..ops.creation import full
        ref = _coerce(batch_ref)
        batch = ref.shape[batch_dim_idx]
        if shape is None:
            shape = [self.hidden_size]
        if dtype is None:
            dtype = str(ref.dtype)

        def build(s):
            if isinstance(s, (list, tuple)) and s and isinstance(
                    s[0], (list, tuple)):
                return type(s)(build(e) for e in s)
            return full([batch] + list(s), init_value, dtype=dtype)
        if isinstance(shape, (list, tuple)) and shape and isinstance(
                shape[0], (list, tuple)):
            return build(shape)
        return build(shape)

    @property
    def state_shape(self):
        return [self.hidden_size]


class RNN(Layer):
    """Run a cell over time (parity: python/paddle/nn/layer/rnn.py RNN).
    The python loop is eager-friendly; under to_static/jit the whole
    unrolled step sequence compiles into one XLA program (static trip
    count — sequences have static shape on TPU)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ..ops.manipulation import stack
        from ..ops.creation import _coerce as coerce
        x = _coerce(inputs)
        time_axis = 0 if self.time_major else 1
        steps = x.shape[time_axis]
        if initial_states is None and hasattr(self.cell,
                                              "get_initial_states"):
            initial_states = self.cell.get_initial_states(
                x, batch_dim_idx=1 if self.time_major else 0)
        states = initial_states
        seq_len = (coerce(sequence_length) if sequence_length is not None
                   else None)
        rev_by_len = seq_len is not None and self.is_reverse
        if rev_by_len:
            # reverse each sequence within its own valid region (padding
            # stays in place), then consume it with a FORWARD masked loop
            # — step t' of the loop sees x[len-1-t'], i.e. the pass
            # starts at each sequence's true end; outputs are mirrored
            # back afterwards
            x = apply(self._rev_by_len_fn(steps, time_axis), x,
                      seq_len)
        order = (range(steps) if (not self.is_reverse or rev_by_len)
                 else range(steps - 1, -1, -1))
        outs = [None] * steps
        for t in order:
            x_t = x[t] if self.time_major else x[:, t]
            out, new_states = (self.cell(x_t, states, **kwargs)
                               if states is not None
                               else self.cell(x_t, **kwargs))
            if seq_len is not None:
                # beyond a sequence's length: output zero, carry state
                out, states = self._mask_step(t, seq_len, out, new_states,
                                              states)
            else:
                states = new_states
            outs[t] = out
        y = stack(outs, axis=time_axis)
        if rev_by_len:
            y = apply(self._rev_by_len_fn(steps, time_axis), y, seq_len)
        return y, states

    @staticmethod
    def _rev_by_len_fn(steps, time_axis):
        def fn(v, lens):
            ts = jnp.arange(steps)
            idx = jnp.where(ts[None, :] < lens[:, None],
                            jnp.clip(lens[:, None] - 1 - ts[None, :], 0),
                            ts[None, :])                    # [B, T]
            if time_axis == 0:
                b = jnp.arange(v.shape[1])
                return v[idx.T, b[None, :]]
            b = jnp.arange(v.shape[0])
            return v[b[:, None], idx]
        return fn

    def _mask_step(self, t, seq_len, out, new_states, old_states):
        def mask_one(new, old):
            def fn(nv, ov, lens):
                keep = (t < lens).reshape((-1,) + (1,) * (nv.ndim - 1))
                return jnp.where(keep, nv, ov)
            return apply(fn, _coerce(new), _coerce(old), seq_len)

        def mask_tree(new, old):
            if isinstance(new, (list, tuple)):
                return type(new)(mask_tree(n, o) for n, o in zip(new, old))
            return mask_one(new, old)

        def zero_out(o):
            def fn(ov, lens):
                keep = (t < lens).reshape((-1,) + (1,) * (ov.ndim - 1))
                return jnp.where(keep, ov, 0)
            return apply(fn, _coerce(o), seq_len)

        return zero_out(out), mask_tree(new_states, old_states)


class BiRNN(Layer):
    """Bidirectional cell wrapper (parity: python/paddle/nn/layer/rnn.py
    BiRNN): concat of forward and reverse RNN outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ..ops.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        y_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length, **kwargs)
        y_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length, **kwargs)
        return concat([y_fw, y_bw], axis=-1), (st_fw, st_bw)
