"""paddle.nn.initializer + ParamAttr.

Reference parity: python/paddle/nn/initializer/*.py and
python/paddle/base/param_attr.py. Initializers are shape→array factories
over the global RNG (matching Paddle's seeded generator semantics).
"""
from __future__ import annotations

import math as pymath

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.random import next_key
from ..tensor import Tensor


def _host_init() -> bool:
    from ..framework.flags import flag_value
    return bool(flag_value("host_init"))


def _np_dtype(dtype):
    """Normalize to a numpy dtype via the framework's converter (handles
    str / np.dtype / jnp scalar types / ml_dtypes bf16 uniformly)."""
    d = dtypes.convert_dtype(dtype)
    return np.dtype(d) if d is not None else np.float32


def _randn(shape, dtype):
    """Standard normal: device jax.random, or host numpy under
    FLAGS_host_init (no compile/execute roundtrip — see flag help)."""
    if _host_init():
        from ..framework.random import default_generator
        r = default_generator().host_rng().standard_normal(tuple(shape))
        return np.asarray(r, dtype=_np_dtype(dtype))
    return jax.random.normal(next_key(), tuple(shape), dtype)


def _randu(shape, dtype, low, high):
    if _host_init():
        from ..framework.random import default_generator
        r = default_generator().host_rng().uniform(low, high, tuple(shape))
        return np.asarray(r, dtype=_np_dtype(dtype))
    return jax.random.uniform(next_key(), tuple(shape), dtype,
                              minval=low, maxval=high)


def _ndtri(p):
    """Inverse standard-normal CDF (Acklam's rational approximation,
    |rel err| < 1.2e-9, refined by one Halley step via math.erf) — exact
    enough for initializer sampling without a scipy dependency."""
    p = np.asarray(p, np.float64)
    a = [-3.969683028665376e+01, 2.209460984245205e+02,
         -2.759285104469687e+02, 1.383577518672690e+02,
         -3.066479806614716e+01, 2.506628277459239e+00]
    b = [-5.447609879822406e+01, 1.615858368580409e+02,
         -1.556989798598866e+02, 6.680131188771972e+01,
         -1.328068155288572e+01]
    c = [-7.784894002430293e-03, -3.223964580411365e-01,
         -2.400758277161838e+00, -2.549732539343734e+00,
         4.374664141464968e+00, 2.938163982698783e+00]
    d = [7.784695709041462e-03, 3.224671290700398e-01,
         2.445134137142996e+00, 3.754408661907416e+00]
    plow, phigh = 0.02425, 1 - 0.02425
    x = np.empty_like(p)
    lo = p < plow
    hi = p > phigh
    mid = ~(lo | hi)
    if lo.any():
        q = np.sqrt(-2 * np.log(p[lo]))
        x[lo] = ((((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4])
                  * q + c[5])
                 / ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1))
    if hi.any():
        q = np.sqrt(-2 * np.log(1 - p[hi]))
        x[hi] = -((((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4])
                   * q + c[5])
                  / ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1))
    if mid.any():
        q = p[mid] - 0.5
        r = q * q
        x[mid] = ((((((a[0] * r + a[1]) * r + a[2]) * r + a[3]) * r + a[4])
                   * r + a[5]) * q
                  / (((((b[0] * r + b[1]) * r + b[2]) * r + b[3]) * r + b[4])
                     * r + 1))
    # one Halley refinement against the exact CDF
    import math
    erf = np.vectorize(math.erf)
    e = 0.5 * (1 + erf(x / np.sqrt(2.0))) - p
    u = e * np.sqrt(2 * np.pi) * np.exp(x * x / 2.0)
    return x - u / (1 + x * u / 2)


def _randtrunc(shape, dtype, a, b):
    if _host_init():
        from ..framework.random import default_generator
        rng = default_generator().host_rng()
        # inverse-CDF sampling: exact for ANY [a, b], including far-tail
        # ranges where rejection sampling would degenerate
        import math
        ca = 0.5 * (1 + math.erf(a / math.sqrt(2.0)))
        cb = 0.5 * (1 + math.erf(b / math.sqrt(2.0)))
        u = rng.uniform(ca, cb, tuple(shape))
        out = _ndtri(u)
        return np.asarray(np.clip(out, a, b), dtype=_np_dtype(dtype))
    return jax.random.truncated_normal(next_key(), a, b, tuple(shape), dtype)


def _cast_host(fn):
    """Numpy dtype promotion undoes a bf16/f16 sample dtype when the
    initializer applies `* std + mean` — re-cast host results to the
    requested dtype after the affine."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, shape, dtype):
        out = fn(self, shape, dtype)
        if isinstance(out, np.ndarray):
            out = np.asarray(out, _np_dtype(dtype))
        return out
    return wrapper


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        if _host_init():
            return np.full(tuple(shape), self.value, _np_dtype(dtype))
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    @_cast_host
    def __call__(self, shape, dtype):
        return _randn(shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    @_cast_host
    def __call__(self, shape, dtype):
        return _randtrunc(shape, dtype, self.a, self.b) * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return _randu(shape, dtype, self.low, self.high)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c/g, *k]
    rf = 1
    for s in shape[2:]:
        rf *= s
    return shape[1] * rf, shape[0] * rf


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    @_cast_host
    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * pymath.sqrt(2.0 / (fi + fo))
        return _randn(shape, dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * pymath.sqrt(6.0 / (fi + fo))
        return _randu(shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    @_cast_host
    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = pymath.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / pymath.sqrt(fi)
        return _randn(shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = pymath.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * pymath.sqrt(3.0 / fi)
        return _randu(shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        return arr.reshape(tuple(shape))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(tuple(shape), dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mink = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mink):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = _randn((max(rows, cols), min(rows, cols)), jnp.float32)
        if isinstance(flat, np.ndarray):  # host path: host QR too
            q, r = np.linalg.qr(flat)
            q = q * np.sign(np.diagonal(r))
            if rows < cols:
                q = q.T
            return np.asarray(self.gain * q[:rows, :cols],
                              _np_dtype(dtype)).reshape(tuple(shape))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(tuple(shape)).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return pymath.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return pymath.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


def _resolve_initializer(init):
    if init is None:
        return XavierUniform()
    if isinstance(init, Initializer):
        return init
    if callable(init):
        return init
    raise TypeError(f"not an initializer: {init!r}")


class ParamAttr:
    """Parity: paddle.ParamAttr (python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if attr is False:
            return False
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"invalid ParamAttr {attr!r}")


# paddle.nn.initializer exposes snake_case aliases too
constant = Constant
normal = Normal
uniform = Uniform


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (parity:
    paddle.nn.initializer.Bilinear)."""

    def __call__(self, shape, dtype):
        import numpy as _np
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects 4-D weights")
        c_out, c_in, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        cw = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = _np.ogrid[:kh, :kw]
        filt = ((1 - _np.abs(og[0] / f_h - ch))
                * (1 - _np.abs(og[1] / f_w - cw)))
        w = _np.zeros(shape, _np.float32)
        for i in range(c_out):
            for j in range(c_in):
                w[i, j] = filt
        import jax.numpy as _jnp
        return _jnp.asarray(w, dtype)


_GLOBAL_INIT = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Parity: paddle.nn.initializer.set_global_initializer — default
    initializers used by create_parameter when neither the attr nor the
    layer specifies one. Pass None, None to reset."""
    _GLOBAL_INIT["weight"] = weight_init
    _GLOBAL_INIT["bias"] = bias_init
