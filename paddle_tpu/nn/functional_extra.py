"""paddle.nn.functional — second tier of the reference surface.

Reference parity: python/paddle/nn/functional/{loss,vision,common,input}.py
(the functions here are the ones not already in functional.py: spatial
transformer ops, unpooling, and the long tail of losses). All lower to
jax.numpy/lax — gathers and scatter-adds are XLA-native and fuse; no
per-op CUDA kernels needed (replaces the corresponding
paddle/phi/kernels/gpu/*_kernel.cu entries).
"""
from __future__ import annotations

import math as pymath

import numpy as np
import jax
import jax.numpy as jnp

from ..ops._dispatch import apply
from ..ops.creation import _coerce

__all__ = [
    "affine_grid", "grid_sample", "fold", "max_unpool1d", "max_unpool2d",
    "max_unpool3d", "channel_shuffle", "bilinear", "pairwise_distance",
    "zeropad2d", "gather_tree", "dice_loss", "log_loss", "npair_loss",
    "poisson_nll_loss", "gaussian_nll_loss", "sigmoid_focal_loss",
    "soft_margin_loss", "multi_label_soft_margin_loss", "multi_margin_loss",
    "triplet_margin_with_distance_loss", "hsigmoid_loss",
    "margin_cross_entropy", "fractional_max_pool2d", "fractional_max_pool3d",
    "class_center_sample", "rnnt_loss",
    "adaptive_log_softmax_with_loss", "sparse_attention",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# ------------------------------------------------------------------ vision --

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Parity: python/paddle/nn/functional/vision.py affine_grid.
    theta: [N, 2, 3] (4-D out_shape) or [N, 3, 4] (5-D out_shape)."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in np.asarray(out_shape.numpy())]
    out_shape = [int(v) for v in out_shape]

    def fn(th):
        nd = len(out_shape) - 2  # 2 or 3 spatial dims

        def axis_coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

        if nd == 2:
            n, _, h, w = out_shape
            ys = axis_coords(h)
            xs = axis_coords(w)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # H,W,3
            # [N,H,W,2] = base @ theta^T
            grid = jnp.einsum("hwk,njk->nhwj", base, th)
            return grid.astype(th.dtype)
        n, _, d, h, w = out_shape
        zs = axis_coords(d)
        ys = axis_coords(h)
        xs = axis_coords(w)
        gz, gy, gx = jnp.meshgrid(zs, ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, gz, jnp.ones_like(gx)], axis=-1)
        grid = jnp.einsum("dhwk,njk->ndhwj", base, th)
        return grid.astype(th.dtype)
    return apply(fn, _coerce(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Parity: python/paddle/nn/functional/vision.py grid_sample (NCHW,
    4-D). Gather-based bilinear/nearest sampling — XLA lowers the gathers
    to efficient dynamic-slice fusions on TPU."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode}")

    def fn(v, g):
        n, c, h, w = v.shape
        gf = g.astype(jnp.float32)
        gx, gy = gf[..., 0], gf[..., 1]  # [N, Ho, Wo]

        def unnormalize(coord, size):
            if align_corners:
                return (coord + 1.0) * 0.5 * (size - 1)
            return ((coord + 1.0) * size - 1.0) * 0.5

        def reflect(coord, size):
            if align_corners:
                span = size - 1
                if span == 0:
                    return jnp.zeros_like(coord)
                coord = jnp.abs(coord)
                period = 2 * span
                coord = coord % period
                return jnp.where(coord > span, period - coord, coord)
            span = size
            coord = jnp.abs(coord + 0.5)
            period = 2 * span
            coord = coord % period
            coord = jnp.where(coord > span, period - coord, coord)
            return jnp.clip(coord - 0.5, 0, size - 1)

        ix = unnormalize(gx, w)
        iy = unnormalize(gy, h)
        if padding_mode == "border":
            ix = jnp.clip(ix, 0, w - 1)
            iy = jnp.clip(iy, 0, h - 1)
        elif padding_mode == "reflection":
            ix = reflect(ix, w)
            iy = reflect(iy, h)

        # shared sampling core (ops/_sampling.py — same helper as
        # roi_align/deform_conv); vmapped over batch, XLA emits one
        # batched gather
        from ..ops import _sampling as S
        ho, wo = iy.shape[1], iy.shape[2]
        samp = S.nearest_zeros if mode == "nearest" else S.bilinear_zeros
        out = jax.vmap(samp)(v, iy.reshape(n, -1), ix.reshape(n, -1))
        return out.reshape(n, c, ho, wo).astype(v.dtype)
    return apply(fn, _coerce(x), _coerce(grid))


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (parity: python/paddle/nn/functional/common.py fold) —
    inverse of unfold: overlapping patch columns scatter-add back into the
    image. Implemented as a static loop over kernel offsets with
    slice-wise .at[].add — XLA turns each into a fused scatter."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings) if not (isinstance(paddings, (list, tuple))
                                     and len(paddings) == 4) else (None, None)
    if ph is None:
        pt, pl, pb, pr = (int(v) for v in paddings)
    else:
        pt, pl, pb, pr = ph, pw, ph, pw
    dh, dw = _pair(dilations)

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        hp, wp = oh + pt + pb, ow + pl + pr
        nh = (hp - (dh * (kh - 1) + 1)) // sh + 1
        nw = (wp - (dw * (kw - 1) + 1)) // sw + 1
        assert nh * nw == L, (
            f"fold: L={L} inconsistent with output_sizes (expect {nh*nw})")
        cols = v.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, hp, wp), v.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :,
                             i * dh:i * dh + nh * sh:sh,
                             j * dw:j * dw + nw * sw:sw].add(
                                 cols[:, :, i, j])
        return out[:, :, pt:pt + oh, pl:pl + ow]
    return apply(fn, _coerce(x))


def _max_unpool(x, indices, ndim, kernel_size, stride, padding, output_size,
                data_format):
    if data_format not in ("NCL", "NCHW", "NCDHW"):
        raise ValueError(f"unsupported data_format {data_format}")
    ks = (kernel_size,) * ndim if isinstance(kernel_size, int) else tuple(
        kernel_size)
    st = ks if stride is None else (
        (stride,) * ndim if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * ndim if isinstance(padding, int) else tuple(padding)

    def fn(v, idx):
        n, c = v.shape[:2]
        in_sp = v.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in output_size)[-ndim:]
        else:
            out_sp = tuple((in_sp[d] - 1) * st[d] - 2 * pd[d] + ks[d]
                           for d in range(ndim))
        flat_out = int(np.prod(out_sp))
        vf = v.reshape(n, c, -1)
        inf = idx.reshape(n, c, -1).astype(jnp.int32)
        out = jnp.zeros((n, c, flat_out), v.dtype)
        # paddle indices are flat positions within the spatial plane
        out = jax.vmap(jax.vmap(
            lambda o, i, val: o.at[i].set(val)))(out, inf, vf)
        return out.reshape(n, c, *out_sp)
    return apply(fn, _coerce(x), _coerce(indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Parity: python/paddle/nn/functional/pooling.py max_unpool1d."""
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Parity: python/paddle/nn/functional/pooling.py max_unpool2d."""
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Parity: python/paddle/nn/functional/pooling.py max_unpool3d."""
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Parity: python/paddle/nn/functional/vision.py channel_shuffle."""
    g = int(groups)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return (v.reshape(n, g, c // g, h, w)
                    .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w))
        n, h, w, c = v.shape
        return (v.reshape(n, h, w, g, c // g)
                .transpose(0, 1, 2, 4, 3).reshape(n, h, w, c))
    return apply(fn, _coerce(x))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Parity: python/paddle/nn/functional/common.py zeropad2d."""
    pl, pr, pt, pb = (int(v) for v in padding)

    def fn(v):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (pt, pb), (pl, pr)]
        else:
            cfg = [(0, 0), (pt, pb), (pl, pr), (0, 0)]
        return jnp.pad(v, cfg)
    return apply(fn, _coerce(x))


def bilinear(x1, x2, weight, bias=None, name=None):
    """Parity: python/paddle/nn/functional/common.py bilinear:
    out[n, o] = x1[n, :] @ W[o] @ x2[n, :] + b[o]."""
    args = [_coerce(x1), _coerce(x2), _coerce(weight)]
    if bias is not None:
        args.append(_coerce(bias))

    def fn(a, b, w, *rest):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    return apply(fn, *args)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """Parity: python/paddle/nn/functional/distance.py pairwise_distance."""
    def fn(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return apply(fn, _coerce(x), _coerce(y))


def gather_tree(ids, parents, name=None):
    """Beam-search ancestry walk (parity: python/paddle/nn/functional/
    input.py gather_tree; upstream phi gather_tree kernel). ids/parents:
    [max_time, batch, beam]. Walks parent pointers backwards with a scan
    (compiler-friendly: fixed trip count, no host loop)."""
    def fn(idv, parv):
        t = idv.shape[0]
        last = idv[t - 1]
        beams = jnp.arange(idv.shape[2], dtype=parv.dtype)
        init = jnp.broadcast_to(beams, idv.shape[1:])

        def step(carry, xs):
            id_t, par_t = xs
            out = jnp.take_along_axis(id_t, carry, axis=1)
            nxt = jnp.take_along_axis(par_t, carry, axis=1)
            return nxt, out

        _, outs = jax.lax.scan(
            step, init, (idv[::-1], parv[::-1]))
        return outs[::-1]
    return apply(fn, _coerce(ids), _coerce(parents))


# ------------------------------------------------------------------ losses --

def dice_loss(input, label, epsilon=1e-5, name=None):
    """Parity: python/paddle/nn/functional/loss.py dice_loss."""
    def fn(v, lab):
        lab_oh = jax.nn.one_hot(lab.squeeze(-1), v.shape[-1], dtype=v.dtype)
        red = tuple(range(1, v.ndim))
        inter = jnp.sum(v * lab_oh, axis=red)
        union = jnp.sum(v, axis=red) + jnp.sum(lab_oh, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))
    return apply(fn, _coerce(input), _coerce(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    """Parity: python/paddle/nn/functional/loss.py log_loss."""
    def fn(v, lab):
        return (-lab * jnp.log(v + epsilon)
                - (1.0 - lab) * jnp.log(1.0 - v + epsilon))
    return apply(fn, _coerce(input), _coerce(label))


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Parity: python/paddle/nn/functional/loss.py npair_loss."""
    def fn(a, p, lab):
        lab = lab.reshape(-1, 1).astype(a.dtype)
        same = (lab == lab.T).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        logits = a @ p.T
        logp = jax.nn.log_softmax(logits, axis=1)
        xent = jnp.mean(jnp.sum(-tgt * logp, axis=1))
        reg = jnp.mean(jnp.sum(a * a, 1) + jnp.sum(p * p, 1)) * (l2_reg / 2)
        return xent + reg
    return apply(fn, _coerce(anchor), _coerce(positive), _coerce(labels))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    """Parity: python/paddle/nn/functional/loss.py poisson_nll_loss."""
    def fn(v, lab):
        if log_input:
            loss = jnp.exp(v) - lab * v
        else:
            loss = v - lab * jnp.log(v + epsilon)
        if full:
            stirling = (lab * jnp.log(lab) - lab
                        + 0.5 * jnp.log(2 * np.pi * lab))
            loss = loss + jnp.where(lab > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply(fn, _coerce(input), _coerce(label))


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Parity: python/paddle/nn/functional/loss.py gaussian_nll_loss."""
    def fn(v, lab, var):
        var = jnp.clip(var, min=epsilon)
        loss = 0.5 * (jnp.log(var) + (v - lab) ** 2 / var)
        if full:
            loss = loss + 0.5 * np.log(2 * np.pi)
        return _reduce(loss, reduction)
    return apply(fn, _coerce(input), _coerce(label), _coerce(variance))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    """Parity: python/paddle/nn/functional/loss.py sigmoid_focal_loss."""
    args = [_coerce(logit), _coerce(label)]
    if normalizer is not None:
        args.append(_coerce(normalizer))

    def fn(lg, lab, *rest):
        p = jax.nn.sigmoid(lg)
        ce = (jnp.maximum(lg, 0) - lg * lab
              + jnp.log1p(jnp.exp(-jnp.abs(lg))))
        pt = p * lab + (1 - p) * (1 - lab)
        at = alpha * lab + (1 - alpha) * (1 - lab)
        loss = at * ((1 - pt) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    return apply(fn, *args)


def soft_margin_loss(input, label, reduction="mean", name=None):
    """Parity: python/paddle/nn/functional/loss.py soft_margin_loss."""
    def fn(v, lab):
        # -log_sigmoid(y*x): stable for large |x| (log1p(exp(..)) overflows)
        return _reduce(-jax.nn.log_sigmoid(lab.astype(v.dtype) * v),
                       reduction)
    return apply(fn, _coerce(input), _coerce(label))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """Parity: python/paddle/nn/functional/loss.py
    multi_label_soft_margin_loss."""
    args = [_coerce(input), _coerce(label)]
    if weight is not None:
        args.append(_coerce(weight))

    def fn(v, lab, *rest):
        lab = lab.astype(v.dtype)
        loss = -(lab * jax.nn.log_sigmoid(v)
                 + (1 - lab) * jax.nn.log_sigmoid(-v))
        if rest:
            loss = loss * rest[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)
    return apply(fn, *args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Parity: python/paddle/nn/functional/loss.py multi_margin_loss."""
    args = [_coerce(input), _coerce(label)]
    if weight is not None:
        args.append(_coerce(weight))

    def fn(v, lab, *rest):
        n, c = v.shape
        lab = lab.astype(jnp.int32)
        correct = jnp.take_along_axis(v, lab[:, None], axis=1)
        m = jnp.maximum(0.0, margin - correct + v) ** p
        if rest:
            m = m * rest[0][lab][:, None]
        mask = jax.nn.one_hot(lab, c, dtype=v.dtype)
        loss = jnp.sum(m * (1 - mask), axis=1) / c
        return _reduce(loss, reduction)
    return apply(fn, *args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Parity: python/paddle/nn/functional/loss.py
    triplet_margin_with_distance_loss."""
    if distance_function is None:
        def distance_function(a, b):
            return pairwise_distance(a, b)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dpn = distance_function(positive, negative)
        from ..ops import math as om
        dn = om.minimum(dn, dpn)

    def fn(dpv, dnv):
        return _reduce(jnp.maximum(0.0, dpv - dnv + margin), reduction)
    return apply(fn, _coerce(dp), _coerce(dn))


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (parity: python/paddle/nn/functional/
    loss.py hsigmoid_loss; upstream phi hsigmoid_loss kernel). Default
    complete-binary-tree coding when no custom path_table/path_code."""
    if (path_table is None) != (path_code is None):
        raise ValueError("path_table and path_code must be given together")
    use_custom = path_table is not None
    args = [_coerce(input), _coerce(label), _coerce(weight)]
    if bias is not None:
        args.append(_coerce(bias))
    if use_custom:
        args.append(_coerce(path_table))
        args.append(_coerce(path_code))

    def fn(x, lab, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        if use_custom:
            table, code = rest
            table = table.astype(jnp.int32)
            code = code.astype(x.dtype)
            valid = (table >= 0).astype(x.dtype)
            tsafe = jnp.maximum(table, 0)
            wsel = w[tsafe]                     # [N, L, D]
            logits = jnp.einsum("nld,nd->nl", wsel, x)
            if b is not None:
                logits = logits + b.reshape(-1)[tsafe]
        else:
            # complete binary tree over num_classes leaves: internal node
            # ids 1..num_classes-1 (root=1); leaf for class c is
            # c + num_classes; path = ancestors of the leaf
            nc = int(num_classes)
            depth = int(np.ceil(np.log2(nc))) if nc > 1 else 1
            leaf = lab.reshape(-1).astype(jnp.int32) + nc
            nodes = []
            codes = []
            cur = leaf
            for _ in range(depth):
                codes.append((cur % 2).astype(x.dtype))
                cur = cur // 2
                nodes.append(cur)
            table = jnp.stack(nodes[::-1], axis=1)   # [N, depth] root-first
            code = jnp.stack(codes[::-1], axis=1)
            valid = (table >= 1).astype(x.dtype)
            # weight is [num_classes - 1, D]: internal node ids 1..nc-1
            # live in rows id-1 (row for the root = 0)
            tsafe = jnp.clip(table - 1, 0, w.shape[0] - 1)
            wsel = w[tsafe]
            logits = jnp.einsum("nld,nd->nl", wsel, x)
            if b is not None:
                logits = logits + b.reshape(-1)[tsafe]
        # bce-with-logits against the path code, masked by valid entries
        per = (jnp.maximum(logits, 0) - logits * code
               + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        loss = jnp.sum(per * valid, axis=1, keepdims=True)
        return jnp.mean(loss)
    return apply(fn, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace margin softmax (parity: python/paddle/nn/functional/
    loss.py margin_cross_entropy; upstream phi margin_cross_entropy
    kernel). logits are cosine similarities; the target class logit is
    remapped cos(m1*theta + m2) - m3 before the scaled softmax."""
    if group is not None:
        # the model-parallel variant (class-dim sharded logits with
        # cross-rank max/sum exchange) lives in the TP layer stack —
        # silently normalizing over a local shard would be wrong
        raise NotImplementedError(
            "margin_cross_entropy(group=...) requires the model-parallel "
            "path; use meta_parallel.ParallelCrossEntropy for sharded "
            "logits or call without group for replicated logits")
    def fn(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        n, c = lg.shape
        tgt = jnp.take_along_axis(lg, lab[:, None], axis=1)  # cos(theta)
        tgt = jnp.clip(tgt, -1.0, 1.0)
        theta = jnp.arccos(tgt)
        mt = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(lab, c, dtype=lg.dtype)
        adj = lg * (1 - oh) + mt * oh
        adj = adj * scale
        logp = jax.nn.log_softmax(adj, axis=1)
        loss = -jnp.take_along_axis(logp, lab[:, None], axis=1)
        red = _reduce(loss, reduction)
        if return_softmax:
            return red, jnp.exp(logp)
        return red
    out = apply(fn, _coerce(logits), _coerce(label))
    return out


# ------------------------------------------------- fractional max pooling --

def _fractional_starts(in_s, out_s, kernel, u):
    """Pseudorandom pooling-region start indices (Graham, "Fractional
    Max-Pooling": a_i = ceil(alpha*(i+u))). Static python/numpy — the
    indices are compile-time constants, so the gather lowers to static
    slices on TPU. Parity: phi fractional_max_pool kernels."""
    alpha = in_s / out_s
    edges = np.ceil(alpha * (np.arange(out_s + 1) + u)).astype(np.int64)
    edges = edges - edges[0]
    edges = np.clip(edges, 0, in_s)
    edges[-1] = in_s
    starts = edges[:-1]
    sizes = np.maximum(edges[1:] - edges[:-1], 1)
    if kernel is not None:
        sizes = np.full_like(sizes, kernel)
        starts = np.minimum(starts, in_s - kernel)
    return starts, sizes


def _fractional_pool(x, output_size, kernel_size, random_u, return_mask,
                     ndim):
    x = _coerce(x)
    shape = tuple(int(s) for s in x._value.shape)
    sp = shape[2:]
    out_sz = ((output_size,) * ndim if not isinstance(output_size,
                                                     (list, tuple))
              else tuple(output_size))
    out_sz = tuple(int(o) if o is not None else s
                   for o, s in zip(out_sz, sp))
    ks = (None,) * ndim if kernel_size is None else (
        (kernel_size,) * ndim if not isinstance(kernel_size, (list, tuple))
        else tuple(kernel_size))
    if random_u is None:
        from ..framework.random import next_key
        u = float(jax.random.uniform(next_key(), ()))
        u = min(max(u, 1e-3), 1.0 - 1e-3)
    else:
        u = float(random_u)
    plans = [_fractional_starts(sp[i], out_sz[i], ks[i], u)
             for i in range(ndim)]

    def _windows(v):
        """Gather each dim's pooling windows: [N, C, o1..on, k1..kn] plus
        the matching validity mask (static index plan → static gathers)."""
        out = v
        valids = []
        for d in range(ndim):
            axis = 2 + d
            starts, sizes = plans[d]
            ksz = int(sizes.max())
            idx = starts[:, None] + np.arange(ksz)[None, :]
            valids.append(idx < (starts + sizes)[:, None])
            idx = np.clip(idx, 0, out.shape[axis] - 1)
            g = jnp.take(out, jnp.asarray(idx.reshape(-1)), axis=axis)
            g = jnp.moveaxis(g, axis, -1)
            g = g.reshape(g.shape[:-1] + (len(starts), ksz))
            out = jnp.moveaxis(g, -2, axis)  # o_d in place, k_d at end
        shape_o = [len(p[0]) for p in plans]
        shape_k = [int(p[1].max()) for p in plans]
        full = np.ones([1] * 2 + shape_o + shape_k, bool)
        for d, vd in enumerate(valids):
            sh = [1] * (2 + 2 * ndim)
            sh[2 + d] = vd.shape[0]
            sh[2 + ndim + d] = vd.shape[1]
            full = full & vd.reshape(sh)
        return out, jnp.asarray(full)

    def fn(v):
        w, valid = _windows(v)
        w = jnp.where(valid, w, jnp.finfo(v.dtype).min)
        out = jnp.max(w, axis=tuple(range(-ndim, 0)))
        if not return_mask:
            return out
        kshape = w.shape[-ndim:]
        flatk = w.reshape(w.shape[:-ndim] + (-1,))
        amax = jnp.argmax(flatk, axis=-1)  # [N, C, o1..on]
        offs = jnp.stack(jnp.unravel_index(amax, kshape), axis=0)
        flat = jnp.zeros_like(amax)
        for d in range(ndim):
            starts = jnp.asarray(plans[d][0])
            sh = [1] * amax.ndim
            sh[2 + d] = starts.shape[0]
            src = starts.reshape(sh) + offs[d]
            flat = flat * sp[d] + src
        return out, flat.astype(jnp.int32)

    return apply(fn, x, _name="fractional_max_pool")


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Parity: python/paddle/nn/functional/pooling.py
    fractional_max_pool2d."""
    return _fractional_pool(x, output_size, kernel_size, random_u,
                            return_mask, 2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Parity: python/paddle/nn/functional/pooling.py
    fractional_max_pool3d."""
    return _fractional_pool(x, output_size, kernel_size, random_u,
                            return_mask, 3)


# ------------------------------------------------------ partial-FC helper --

def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """Sample class centers for partial-FC margin softmax (parity:
    python/paddle/nn/functional/common.py class_center_sample; upstream
    phi class_center_sample kernel). Returns (remapped_label,
    sampled_class_indices). Host-side op: labels are concrete data, the
    sampled set is a static-size [num_samples] vector (TPU-friendly)."""
    from ..tensor import Tensor
    lab = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    lab = lab.reshape(-1).astype(np.int64)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos  # all positives are always kept (reference semantics)
    else:
        from ..framework.random import next_key
        neg_pool = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos)
        k = num_samples - len(pos)
        perm = np.asarray(jax.random.permutation(next_key(),
                                                 len(neg_pool)))[:k]
        sampled = np.concatenate([pos, neg_pool[perm]])
    sampled = np.sort(sampled)
    remap = np.full((num_classes,), -1, dtype=np.int64)
    remap[sampled] = np.arange(len(sampled))
    new_lab = remap[lab]
    from ..ops.creation import to_tensor
    return to_tensor(new_lab), to_tensor(sampled)


# --------------------------------------------------------------- RNN-T loss --

def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (parity: python/paddle/nn/functional/loss.py
    rnnt_loss; upstream warprnnt kernel). Log-semiring forward DP over
    the (T, U) lattice as a lax.scan over time — compiler-friendly
    (static trip count, masked tails) and reverse-differentiable, so no
    hand-written backward is needed.

    FastEmit (Yu et al. 2021): the reference warprnnt kernel scales the
    label-emission gradient by (1 + lambda) while reporting the
    unregularized loss. Reproduced here with a zero-valued loss term
    whose gradient is the DP's gradient with blank log-probs
    stop-gradiented (emit-only gradient).

    input: [B, T, U+1, V] log-probs (or logits — normalized here),
    label: [B, U] int, input_lengths: [B], label_lengths: [B].
    """
    args = [_coerce(a) for a in (input, label, input_lengths,
                                 label_lengths)]

    def fn(acts, labels, t_lens, u_lens):
        acts = jax.nn.log_softmax(acts, axis=-1)
        b, t_max, u_max1, _v = acts.shape
        u_max = u_max1 - 1
        labels = labels.astype(jnp.int32)
        lab_lp = jnp.take_along_axis(
            acts[:, :, :u_max, :], labels[:, None, :, None],
            axis=3)[..., 0]                               # [B,T,U]
        neg_inf = jnp.float32(-1e30)

        def dp_nll(blank_lp):
            # alpha over u for one time step; emits move along u
            def u_step(alpha_prev_t, t):
                # horizontal (blank) move from t-1 keeps u
                from_blank = jnp.where(
                    t > 0,
                    alpha_prev_t + blank_lp[:, jnp.maximum(t - 1, 0), :],
                    jnp.where(jnp.arange(u_max1)[None, :] == 0, 0.0,
                              neg_inf))
                # vertical (label) moves within time t: prefix recurrence
                def emit_scan(carry, u):
                    prev = carry  # alpha[t, u-1]
                    cur = jnp.logaddexp(
                        from_blank[:, u],
                        prev + jnp.where(u > 0,
                                         lab_lp[:, t, jnp.maximum(u - 1, 0)],
                                         neg_inf))
                    return cur, cur
                init = jnp.full((b,), neg_inf)
                _, cols = jax.lax.scan(emit_scan, init, jnp.arange(u_max1))
                return jnp.transpose(cols)                # [B, U+1]

            def t_step(alpha, t):
                new = u_step(alpha, t)
                return new, new

            alpha0 = jnp.full((b, u_max1), neg_inf)
            _, alphas = jax.lax.scan(t_step, alpha0, jnp.arange(t_max))
            alphas = jnp.moveaxis(alphas, 0, 1)           # [B,T,U+1]
            tl = t_lens.astype(jnp.int32) - 1
            ul = u_lens.astype(jnp.int32)
            final = alphas[jnp.arange(b), tl, ul]         # alpha[T-1, U]
            last_blank = blank_lp[jnp.arange(b), tl, ul]
            return -(final + last_blank)

        blank_lp = acts[..., blank]                       # [B,T,U+1]
        nll = dp_nll(blank_lp)
        if fastemit_lambda:
            # zero-valued term whose gradient is the emit-only gradient:
            # reported loss matches the unregularized reference value
            fe = dp_nll(jax.lax.stop_gradient(blank_lp))
            nll = nll + fastemit_lambda * (fe - jax.lax.stop_gradient(fe))
        return _reduce(nll, reduction)

    return apply(fn, *args, _name="rnnt_loss")


# ------------------------------------------- adaptive softmax with loss --

def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (parity: python/paddle/nn/functional/loss.py
    adaptive_log_softmax_with_loss; Grave et al. 2017). The head predicts
    the frequent classes plus one slot per tail cluster; each tail
    cluster factorizes through a low-rank projection. Returns
    (output [N] per-sample target log-prob, loss = -mean(output)).

    head_weight: [in, cutoffs[0] + n_clusters]; tail_weights: list of
    (proj [in, hsz_i], cls [hsz_i, cluster_size_i]) pairs."""
    n_clusters = len(tail_weights)
    cutoffs = list(cutoffs)
    shortlist = cutoffs[0]
    n_classes = cutoffs[-1]

    lab_t = _coerce(label)
    # eager label-range validation (reference raises; a traced label is
    # clamped inside fn since data-dependent raising can't compile)
    try:
        lab_np = np.asarray(lab_t._value)
        if lab_np.size and (lab_np.min() < 0 or lab_np.max() >= n_classes):
            raise ValueError(
                f"adaptive_log_softmax_with_loss: target values must be "
                f"in [0, {n_classes - 1}], got range "
                f"[{lab_np.min()}, {lab_np.max()}]")
    except (TypeError, jax.errors.TracerArrayConversionError):
        pass  # tracer: no concrete values to validate

    args = [_coerce(input), lab_t, _coerce(head_weight)]
    flat_tails = []
    for pr, cl in tail_weights:
        flat_tails += [_coerce(pr), _coerce(cl)]
    args += flat_tails
    has_bias = head_bias is not None
    if has_bias:
        args.append(_coerce(head_bias))

    def fn(x, lab, hw, *rest):
        tails = rest[:2 * n_clusters]
        hb = rest[2 * n_clusters] if has_bias else None
        lab = lab.reshape(-1).astype(jnp.int32)
        head = x @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, axis=-1)   # [N, S + C]
        # shortlist targets read straight from the head
        out = jnp.take_along_axis(
            head_lp, jnp.clip(lab, 0, shortlist - 1)[:, None],
            axis=1)[:, 0]
        for i in range(n_clusters):
            lo = cutoffs[i]
            hi = cutoffs[i + 1]
            proj, cls = tails[2 * i], tails[2 * i + 1]
            clus_lp = jax.nn.log_softmax((x @ proj) @ cls, axis=-1)
            in_cl = (lab >= lo) & (lab < hi)
            idx = jnp.clip(lab - lo, 0, hi - lo - 1)
            lp_in = head_lp[:, shortlist + i] + jnp.take_along_axis(
                clus_lp, idx[:, None], axis=1)[:, 0]
            out = jnp.where(in_cl, lp_in, out)
        return out, -jnp.mean(out)

    return apply(fn, *args, _name="adaptive_log_softmax")


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block/CSR-sparse attention (parity: paddle.nn.functional.
    sparse_attention, phi sparse_attention CUDA kernel). q/k/v
    [B, H, S, D]; per-(batch, head) CSR pattern — offset [B, H, S+1],
    columns [B, H, nnz]. TPU-native formulation: gather scores at the
    nnz coordinates and run a segment-softmax per query row — static
    shapes (nnz fixed), one fused gather/scatter pair, no S x S mask.
    Masks are additive (0 keep / -inf drop), matching the reference."""
    import jax as _jax
    from ..ops._dispatch import apply as _apply
    from ..ops.creation import _coerce as _c

    args = [_c(query), _c(key), _c(value), _c(sparse_csr_offset),
            _c(sparse_csr_columns)]
    has_kpm = key_padding_mask is not None
    if has_kpm:
        args.append(_c(key_padding_mask))
    has_am = attn_mask is not None
    if has_am:
        args.append(_c(attn_mask))

    def fn(q, k, v, off, cols, *rest):
        it = iter(rest)
        kpm = next(it) if has_kpm else None
        am = next(it) if has_am else None
        B, H, S, D = q.shape
        nnz = cols.shape[-1]
        j = jnp.arange(nnz)
        rows = _jax.vmap(_jax.vmap(
            lambda o: jnp.searchsorted(o, j, side="right") - 1))(
                off.astype(jnp.int32))                       # [B, H, nnz]
        rows = jnp.clip(rows, 0, S - 1)
        colsc = jnp.clip(cols.astype(jnp.int32), 0, S - 1)
        qg = jnp.take_along_axis(q, rows[..., None], axis=2)
        kg = jnp.take_along_axis(k, colsc[..., None], axis=2)
        s = jnp.einsum("bhnd,bhnd->bhn", qg.astype(jnp.float32),
                       kg.astype(jnp.float32)) / jnp.sqrt(
                           jnp.float32(D))
        if kpm is not None:   # [B, S] additive over key positions
            s = s + jnp.take_along_axis(
                kpm.astype(jnp.float32)[:, None, :].repeat(H, 1),
                colsc, axis=2)
        if am is not None:    # [S, S] additive over (row, col)
            s = s + am.astype(jnp.float32)[rows, colsc]

        def per_head(s_h, rows_h, v_h, cols_h):
            m = _jax.ops.segment_max(s_h, rows_h, num_segments=S)
            e = jnp.exp(s_h - m[rows_h])
            z = _jax.ops.segment_sum(e, rows_h, num_segments=S)
            p = e / jnp.where(z == 0.0, 1.0, z)[rows_h]
            vg = v_h[cols_h].astype(jnp.float32)
            return _jax.ops.segment_sum(p[:, None] * vg, rows_h,
                                        num_segments=S)

        out = _jax.vmap(_jax.vmap(per_head))(s, rows, v, colsc)
        return out.astype(q.dtype)

    return _apply(fn, *args, _name="sparse_attention")
