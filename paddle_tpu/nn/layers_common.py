"""Common layers (parity: python/paddle/nn/layer/{common,conv,norm,pooling,
activation}.py)."""
from __future__ import annotations

import collections
import math as pymath
import numbers

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, Parameter
from ..framework import dtype as dtypes
from .layer_base import Layer
from .initializer import (ParamAttr, Constant, Normal, Uniform, XavierUniform,
                          KaimingUniform, _resolve_initializer)
from . import functional as F


# ------------------------------------------------------------- containers --
class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        if isinstance(idx, str):
            return self._sub_layers[idx]
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(len(self) + idx if idx < 0 else idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        l = self._sub_layers[key]
        del self._sub_layers[key]
        return l

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, collections.OrderedDict)) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


# ------------------------------------------------------------------ linear --
class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (paddle layout).
    Parity: python/paddle/nn/layer/common.py::Linear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


# ------------------------------------------------------------------- convs --
class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, padding_mode, weight_attr, bias_attr,
                 data_format, ndim, transpose=False, output_padding=0):
        super().__init__()
        if in_channels % groups != 0:
            raise ValueError("in_channels must be divisible by groups")
        self._in_channels = in_channels
        self._out_channels = out_channels
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * ndim
        self._kernel_size = tuple(int(k) for k in ks)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            w_shape = [in_channels, out_channels // groups, *self._kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups, *self._kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in, negative_slope=pymath.sqrt(5.0), nonlinearity="leaky_relu"))
        bound = 1.0 / pymath.sqrt(fan_in)
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound)) \
            if bias_attr is not False else None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 1, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 2, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 3, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


# ------------------------------------------------------------------- norms --
class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, numbers.Integral):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """TPU hot-path norm for Llama-family (ecosystem parity: PaddleNLP
    llama RMSNorm; fused kernel: kernels/norm.py)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        from ..ops._dispatch import apply
        from ..kernels.norm import fused_rms_norm
        return apply(lambda v, w: fused_rms_norm(v, w, self._epsilon),
                     x, self.weight, _name="rms_norm")


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       dtypes.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          dtypes.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class BatchNorm(_BatchNormBase):
    """Old-style paddle.nn.BatchNorm (dygraph legacy API)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync falls out of jit+psum over the data axis —
    kept as an API-compatible alias whose conversion hook is a no-op.
    Parity: python/paddle/nn/layer/norm.py::SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


class SpectralNorm(Layer):
    """Parity: paddle.nn.SpectralNorm (upstream phi spectral_norm
    kernel): weight / sigma_max, sigma estimated by power iteration
    with persistent u/v buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        import numpy as _np
        from ..framework.random import next_key
        self._dim = dim
        self._iters = power_iters
        self._eps = epsilon
        h = int(weight_shape[dim])
        w = int(_np.prod(weight_shape)) // h
        k1, k2 = jax.random.split(next_key())
        u0 = jax.random.normal(k1, (h,), jnp.float32)
        v0 = jax.random.normal(k2, (w,), jnp.float32)
        self.register_buffer("weight_u",
                             Tensor(u0 / (jnp.linalg.norm(u0) + epsilon)))
        self.register_buffer("weight_v",
                             Tensor(v0 / (jnp.linalg.norm(v0) + epsilon)))

    def forward(self, weight):
        from ..ops._dispatch import apply
        from ..ops.creation import _coerce
        dim, iters, eps = self._dim, self._iters, self._eps

        def fn(wv, u, v):
            perm = [dim] + [d for d in range(wv.ndim) if d != dim]
            mat = wv.transpose(perm).reshape(wv.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return wv / sigma, u, v

        out, new_u, new_v = apply(fn, _coerce(weight), self.weight_u,
                                  self.weight_v)
        # persistent power-iteration state (detached buffers)
        self.weight_u._value = new_u._value
        self.weight_v._value = new_v._value
        return out


# --------------------------------------------------------------- embedding --
class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._value = self.weight._value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


# ---------------------------------------------------------------- dropouts --
class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


# ------------------------------------------------------------- activations --
def _act_layer(fname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            sig_kw = {k: v for k, v in kwargs.items() if k != "name"}
            self._kwargs.update(sig_kw)
            if args:
                # positional args map per-activation; store generically
                self._args = args
            else:
                self._args = ()

        def forward(self, x):
            return getattr(F, fname)(x, *self._args, **self._kwargs)
    _Act.__name__ = fname.title().replace("_", "")
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
Sigmoid = _act_layer("sigmoid")
LogSigmoid = _act_layer("log_sigmoid")
Tanh = _act_layer("tanh")
Tanhshrink = _act_layer("tanhshrink")
Hardshrink = _act_layer("hardshrink")
Hardsigmoid = _act_layer("hardsigmoid")
Hardswish = _act_layer("hardswish")
Hardtanh = _act_layer("hardtanh")
Softshrink = _act_layer("softshrink")
Softsign = _act_layer("softsign")
Swish = _act_layer("swish")
Silu = _act_layer("silu")
Mish = _act_layer("mish")
SELU = _act_layer("selu")
CELU = _act_layer("celu")
ELU = _act_layer("elu")
GELU = _act_layer("gelu")
LeakyReLU = _act_layer("leaky_relu")
Softplus = _act_layer("softplus")
Maxout = _act_layer("maxout")
GLU = _act_layer("glu")
ThresholdedReLU = _act_layer("thresholded_relu")


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower = lower
        self._upper = upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW / CHW inputs."""
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        assert x.ndim in (3, 4), "Softmax2D expects 3-D or 4-D input"
        return F.softmax(x, axis=-3)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


# ----------------------------------------------------------------- pooling --
def _pool_layer(fname):
    class _Pool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
            super().__init__()
            self.kernel_size = kernel_size
            self.stride = stride
            self.padding = padding
            self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

        def forward(self, x):
            return getattr(F, fname)(x, self.kernel_size, self.stride,
                                     self.padding, **self.kwargs)
    _Pool.__name__ = fname.title().replace("_", "")
    return _Pool


class LPPool1D(Layer):
    """Power-average pooling (parity: paddle.nn.LPPool1D)."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size,
                           self.stride, self.padding, self.ceil_mode,
                           self.data_format)


class LPPool2D(Layer):
    """Power-average pooling (parity: paddle.nn.LPPool2D)."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size,
                           self.stride, self.padding, self.ceil_mode,
                           self.data_format)


MaxPool1D = _pool_layer("max_pool1d")
MaxPool2D = _pool_layer("max_pool2d")
MaxPool3D = _pool_layer("max_pool3d")
AvgPool1D = _pool_layer("avg_pool1d")
AvgPool2D = _pool_layer("avg_pool2d")
AvgPool3D = _pool_layer("avg_pool3d")


def _adaptive_pool_layer(fname):
    class _Pool(Layer):
        def __init__(self, output_size, **kwargs):
            super().__init__()
            self.output_size = output_size

        def forward(self, x):
            return getattr(F, fname)(x, self.output_size)
    _Pool.__name__ = fname.title().replace("_", "")
    return _Pool


AdaptiveAvgPool1D = _adaptive_pool_layer("adaptive_avg_pool1d")
AdaptiveAvgPool2D = _adaptive_pool_layer("adaptive_avg_pool2d")
AdaptiveAvgPool3D = _adaptive_pool_layer("adaptive_avg_pool3d")
AdaptiveMaxPool1D = _adaptive_pool_layer("adaptive_max_pool1d")
AdaptiveMaxPool2D = _adaptive_pool_layer("adaptive_max_pool2d")
AdaptiveMaxPool3D = _adaptive_pool_layer("adaptive_max_pool3d")


class _FractionalMaxPoolNd(Layer):
    _ndim = 2

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        fname = f"fractional_max_pool{self._ndim}d"
        o, k, u, m = self._args
        return getattr(F, fname)(x, o, kernel_size=k, random_u=u,
                                 return_mask=m)


class FractionalMaxPool2D(_FractionalMaxPoolNd):
    _ndim = 2


class FractionalMaxPool3D(_FractionalMaxPoolNd):
    _ndim = 3


# ----------------------------------------------------------------- padding --
class _PadNd(Layer):
    _nsp = {"NCL": 1, "NLC": 1, "NCHW": 2, "NHWC": 2,
            "NCDHW": 3, "NDHWC": 3}

    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * (2 * self._nsp.get(data_format, 1))
        self._padding = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._padding, self._mode, self._value,
                     self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad1D(Pad1D):
    pass


class ZeroPad2D(Pad2D):
    pass


class ZeroPad3D(Pad3D):
    pass


# -------------------------------------------------------------------- misc --
class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (size, scale_factor, mode, align_corners, align_mode,
                      data_format)

    def forward(self, x):
        return F.interpolate(x, *self._args)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = upscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._factor, self._data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = downscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis = axis
        self._eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self._axis, self._eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ..ops._dispatch import apply
        def fn(a, b, w, *bias):
            out = jnp.einsum("bi,oij,bj->bo", a, w, b)
            return out + bias[0] if bias else out
        args = [x1, x2, self.weight]
        if self.bias is not None:
            args.append(self.bias)
        return apply(fn, *args)


class Fold(Layer):
    """Parity: python/paddle/nn/layer/common.py Fold."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        from .functional_extra import fold
        return fold(x, *self._args)


class MaxUnPool1D(Layer):
    """Parity: python/paddle/nn/layer/pooling.py MaxUnPool1D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        from .functional_extra import max_unpool1d
        ks, st, pd, df, os_ = self._args
        return max_unpool1d(x, indices, ks, st, pd, df, os_)


class MaxUnPool2D(Layer):
    """Parity: python/paddle/nn/layer/pooling.py MaxUnPool2D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        from .functional_extra import max_unpool2d
        ks, st, pd, df, os_ = self._args
        return max_unpool2d(x, indices, ks, st, pd, df, os_)


class MaxUnPool3D(Layer):
    """Parity: python/paddle/nn/layer/pooling.py MaxUnPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        from .functional_extra import max_unpool3d
        ks, st, pd, df, os_ = self._args
        return max_unpool3d(x, indices, ks, st, pd, df, os_)


class PairwiseDistance(Layer):
    """Parity: python/paddle/nn/layer/distance.py PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from .functional_extra import pairwise_distance
        return pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Unflatten(Layer):
    """Parity: python/paddle/nn/layer/common.py Unflatten."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self._shape = axis, shape

    def forward(self, x):
        from ..ops.extras import unflatten
        return unflatten(x, self.axis, self._shape)


class ChannelShuffle(Layer):
    """Parity: python/paddle/nn/layer/vision.py ChannelShuffle."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        from .functional_extra import channel_shuffle
        return channel_shuffle(x, self.groups, self.data_format)
