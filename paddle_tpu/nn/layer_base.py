"""paddle.nn.Layer — the module base class.

Reference parity: python/paddle/nn/layer/layers.py (Layer): parameter /
buffer / sublayer registries, forward pre/post hooks, train/eval mode,
state_dict / set_state_dict, apply, to(dtype/device), named_* iterators.

TPU-native note: parameters are `Parameter` tensors (rebindable jax
arrays); `state_dict` yields the live tensors so a functional bridge
(paddle_tpu.jit / distributed engines) can lift the whole layer into a
pure pytree-of-arrays function for `jax.jit`/`pjit`.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, Parameter
from ..framework import dtype as dtypes
from .initializer import _resolve_initializer, ParamAttr, XavierUniform, Constant


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------ naming --
    def full_name(self):
        return self._name_scope

    # -------------------------------------------------------- attributes --
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif params is not None and name in params:
            if value is not None and not isinstance(value, Parameter):
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
            params[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value if (value is None or isinstance(value, Tensor)) \
                else Tensor(value)
        elif layers is not None and name in layers:
            layers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # ------------------------------------------------------- registration --
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Parity: Layer.create_parameter → LayerHelper.create_parameter."""
        d = dtypes.convert_dtype(dtype) or self._dtype
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        from .initializer import _GLOBAL_INIT
        # set_global_initializer overrides the layers' built-in defaults
        # but never an explicit ParamAttr initializer (paddle semantics)
        g = _GLOBAL_INIT["bias"] if is_bias else _GLOBAL_INIT["weight"]
        init = attr.initializer or g or default_initializer or \
            (Constant(0.0) if is_bias else XavierUniform())
        value = _resolve_initializer(init)(shape, d)
        if isinstance(value, np.ndarray):
            # host-init (numpy) samples: force an XLA-OWNED device copy.
            # jnp.asarray(np) zero-copy-aliases ~half the time on the CPU
            # backend (alignment-dependent), and compiled train steps /
            # fused optimizers DONATE param buffers — donating an aliased
            # buffer frees numpy-allocated memory through XLA's
            # deallocator (heap corruption; segfaulted the CPU bench).
            value = jnp.array(jnp.asarray(value), copy=True)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        if not attr.trainable:
            p.stop_gradient = True
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        d = dtypes.convert_dtype(dtype) or self._dtype
        t = Tensor(jnp.zeros((), d))
        t.name = name
        return t

    # ---------------------------------------------------------- traversal --
    def named_parameters(self, prefix="", include_sublayers=True,
                         _seen=None) -> Iterator[Tuple[str, Parameter]]:
        # _seen is shared across the WHOLE recursion: a tied parameter
        # (e.g. an LM head holding the embedding weight) must be yielded
        # once, or optimizers would apply its update twice per step
        seen = _seen if _seen is not None else set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for item in layer.named_parameters(sub_prefix, _seen=seen):
                    yield item

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for item in layer.named_buffers(sub_prefix):
                    yield item

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            for item in layer.named_sublayers(sub_prefix):
                yield item

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -------------------------------------------------------------- modes --
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -------------------------------------------------------------- hooks --
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------ forward --
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ----------------------------------------------------------- state io --
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        sync = getattr(self, "_deferred_sync", None)
        if sync is not None:
            # a compiled train step (e.g. PipelineTrainStep) keeps the
            # authoritative params device-side; flush before reading
            sync()
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                dest[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is not None:
                    layer.state_dict(dest, True,
                                     structured_name_prefix + lname + ".")
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Returns (missing_keys, unexpected_keys) — parity with paddle."""
        own = self.state_dict()
        missing, unexpected = [], []
        matched = set()
        for k, v in state_dict.items():
            if k in own:
                t = own[k]
                arr = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(arr.shape) != tuple(t._value.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: got {tuple(arr.shape)}, "
                        f"expected {tuple(t._value.shape)}")
                t._value = arr.astype(t._value.dtype)
                matched.add(k)
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        inval = getattr(self, "_deferred_invalidate", None)
        if inval is not None:
            # a compiled train step caches device-side copies of these
            # params (e.g. stage-stacked pipeline weights); tell it to
            # re-read from the layer tensors on its next step
            inval()
        return missing, unexpected

    load_dict = set_state_dict

    # ---------------------------------------------------------- conversion --
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(dtypes.convert_dtype(dtype))
        if device is not None:
            from ..framework.place import _parse_place
            dev = _parse_place(device).jax_device
            for t in list(self.parameters()) + list(self.buffers()):
                t._value = jax.device_put(t._value, dev)
        return self

    def _to_dtype(self, d):
        for t in self.parameters():
            if dtypes.is_floating_point(t.dtype):
                t._value = t._value.astype(d)
        for b in self.buffers():
            if dtypes.is_floating_point(b.dtype):
                b._value = b._value.astype(d)
        self._dtype = d
        return self

    def astype(self, dtype):
        return self._to_dtype(dtypes.convert_dtype(dtype))

    def float(self):
        return self._to_dtype(dtypes.float32)

    def bfloat16(self):
        return self._to_dtype(dtypes.bfloat16)

    def float16(self):
        return self._to_dtype(dtypes.float16)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def extra_repr(self):
        return ""
