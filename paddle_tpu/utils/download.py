"""paddle.utils.download parity (python/paddle/utils/download.py).

This environment is zero-egress: nothing can be fetched. The cache-lookup
half of the API works (weights a user has placed under the cache dir, or
any readable path, resolve normally); an actual network fetch raises with
instructions instead of hanging on a dead socket.
"""
from __future__ import annotations

import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")


def _cache_path(url, root):
    fname = osp.split(url)[-1]
    return osp.join(root, fname)


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    if osp.exists(url):  # already a local path
        return url
    path = _cache_path(url, root_dir)
    if check_exist and osp.exists(path):
        return path
    raise RuntimeError(
        f"cannot download '{url}': this environment has no network "
        f"egress. Place the file at '{path}' (or pass a local path) and "
        "retry.")


def get_weights_path_from_url(url, md5sum=None):
    os.makedirs(WEIGHTS_HOME, exist_ok=True)
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
