"""paddle.utils.cpp_extension parity (python/paddle/utils/cpp_extension/).

Custom C++ operators here are plain C extensions built with setuptools
(the baked toolchain has g++/cmake/ninja; pybind11 is NOT shipped, so
extensions use the CPython C API or ctypes — see csrc/ for the
in-tree examples: tcp_store.cc, shm_channel.cc, capi.cc built by
csrc/Makefile). CUDA-specific pieces have no TPU meaning: device
compute belongs in Pallas kernels, not custom device ops."""
from __future__ import annotations

__all__ = ["CppExtension", "CUDAExtension", "setup", "load",
           "get_build_directory"]


def get_build_directory():
    import os
    d = os.path.expanduser("~/.cache/paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


def CppExtension(sources, **kwargs):
    """Build descriptor for a C++ custom op (setuptools.Extension).
    Extra Extension options (include_dirs, extra_compile_args, ...)
    pass through as keywords."""
    from setuptools import Extension
    name = kwargs.pop("name", "paddle_custom_ext")
    return Extension(name, sources=list(sources), **kwargs)


def CUDAExtension(sources, *args, **kwargs):
    raise NotImplementedError(
        "CUDA custom ops have no TPU lowering; write device compute as "
        "a Pallas kernel (paddle_tpu/kernels/ shows the patterns) and "
        "host-side native code as a CppExtension")


def setup(**kwargs):
    """Parity: cpp_extension.setup — delegates to setuptools.setup.
    When invoked with no command (`python setup.py`), defaults to
    `build_ext --inplace`; an explicit command line wins."""
    import sys
    from setuptools import setup as _setup
    if len(sys.argv) < 2 and "script_args" not in kwargs:
        kwargs["script_args"] = ["build_ext", "--inplace"]
    return _setup(**kwargs)


def load(name, sources, extra_cxx_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None, verbose=False,
         extra_cuda_cflags=None):
    """JIT-compile a C extension from sources and import it (parity:
    cpp_extension.load). Uses the CPython C API toolchain in-place.
    Rebuilds when sources are newer OR the build configuration
    (source list / flags / includes) changed since the cached build."""
    import hashlib
    import importlib.util
    import os
    import subprocess
    import sysconfig

    if extra_cuda_cflags:
        import warnings
        warnings.warn("extra_cuda_cflags ignored: no CUDA toolchain here; "
                      "device kernels are Pallas")

    bdir = build_directory or get_build_directory()
    os.makedirs(bdir, exist_ok=True)
    so_path = os.path.join(bdir, f"{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    cmd = ["g++", "-O2", "-shared", "-fPIC",
           f"-I{sysconfig.get_paths()['include']}"]
    for inc in (extra_include_paths or []):
        cmd.append(f"-I{inc}")
    cmd += (extra_cxx_cflags or [])
    cmd += srcs + ["-o", so_path] + (extra_ldflags or [])
    sig = hashlib.sha256(" ".join(cmd).encode()).hexdigest()
    sig_path = so_path + ".sig"
    newest_src = max(os.path.getmtime(s) for s in srcs)

    # serialize concurrent ranks/workers building the same extension:
    # exclusive flock around the stale-check+build, and the .so lands via
    # atomic rename so a reader never imports a half-written file
    import fcntl
    with open(so_path + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        stale = (not os.path.exists(so_path)
                 or os.path.getmtime(so_path) < newest_src
                 or not os.path.exists(sig_path)
                 or open(sig_path).read() != sig)
        if stale:
            tmp_so = so_path + f".tmp{os.getpid()}"
            build_cmd = [tmp_so if a == so_path else a for a in cmd]
            if verbose:
                print(" ".join(build_cmd))
            res = subprocess.run(build_cmd, capture_output=not verbose,
                                 text=True)
            if res.returncode != 0:
                raise RuntimeError(
                    "cpp_extension.load: compilation failed\n"
                    + (res.stderr or "") + (res.stdout or ""))
            os.replace(tmp_so, so_path)
            with open(sig_path + ".tmp", "w") as f:
                f.write(sig)
            os.replace(sig_path + ".tmp", sig_path)
    spec = importlib.util.spec_from_file_location(name, so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
