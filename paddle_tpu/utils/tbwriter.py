"""Minimal TensorBoard event-file writer (no tensorboard/visualdl deps).

Reference parity: VisualDL's LogWriter (the reference ecosystem's metric
logger, SURVEY §5.5). TPU-native stance: metrics write standard
TFRecord/tf.Event files that TensorBoard (and VisualDL's TB-import) read
directly; the protobuf wire encoding for the tiny Event/Summary subset we
need (scalars + text) is hand-rolled below, so the writer has zero
dependencies.

Wire format notes:
- protobuf: varint keys (field_number << 3 | wire_type); doubles are
  64-bit (wire type 1), floats 32-bit (5), strings/submessages
  length-delimited (2), ints varint (0).
- TFRecord framing: len(u64 LE) + masked_crc32c(len) + payload +
  masked_crc32c(payload), with the "masked" rotation TensorFlow uses.
"""
from __future__ import annotations

import os
import struct
import time
from typing import Optional

__all__ = ["LogWriter", "SummaryWriter"]


# ----------------------------------------------------------- crc32c ------
def _make_crc_table():
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_crc_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------ proto encoding ---
def _varint(n: int) -> bytes:
    # protobuf encodes negative int64 as two's-complement 64-bit varint;
    # without the mask python's arithmetic shift would loop forever
    if n < 0:
        n &= (1 << 64) - 1
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            out += bytes([b7])
            return out


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _str_field(field: int, s: bytes) -> bytes:
    return _key(field, 2) + _varint(len(s)) + s


def _float_field(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _double_field(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _int_field(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v)


def _summary_value(tag: str, value: float) -> bytes:
    # Summary.Value{ tag=1, simple_value=2 }
    return _str_field(1, tag.encode()) + _float_field(2, float(value))


def _event(wall_time: float, step: Optional[int] = None,
           file_version: Optional[str] = None,
           summary_values: Optional[list] = None) -> bytes:
    # Event{ wall_time=1(double), step=2(int64), file_version=3(string),
    #        summary=5(Summary{ repeated value=1 }) }
    msg = _double_field(1, wall_time)
    if step is not None:
        msg += _int_field(2, int(step))
    if file_version is not None:
        msg += _str_field(3, file_version.encode())
    if summary_values:
        summary = b"".join(_str_field(1, v) for v in summary_values)
        msg += _str_field(5, summary)
    return msg


# -------------------------------------------------------------- writer ---
class LogWriter:
    """VisualDL-shaped scalar logger emitting TensorBoard event files.

    with LogWriter(logdir="./log") as w:
        w.add_scalar(tag="train/loss", value=loss, step=i)
    """

    def __init__(self, logdir: str = "./log", file_name: str = "",
                 display_name: str = "", **kwargs):
        os.makedirs(logdir, exist_ok=True)
        name = file_name or (
            f"events.out.tfevents.{int(time.time())}.paddle_tpu")
        self._path = os.path.join(logdir, name)
        self._f = open(self._path, "ab")
        self._write_record(_event(time.time(),
                                  file_version="brain.Event:2"))

    @property
    def logdir(self):
        return os.path.dirname(self._path)

    def _write_record(self, payload: bytes):
        hdr = struct.pack("<Q", len(payload))
        self._f.write(hdr)
        self._f.write(struct.pack("<I", _masked_crc(hdr)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def add_scalar(self, tag: str, value, step: int = 0, walltime=None):
        self._write_record(_event(
            walltime if walltime is not None else time.time(), step,
            summary_values=[_summary_value(tag, float(value))]))

    def add_scalars(self, main_tag: str, tag_value_dict, step: int = 0):
        for k, v in tag_value_dict.items():
            self.add_scalar(f"{main_tag}/{k}", v, step)

    def add_text(self, tag: str, text_string: str, step: int = 0):
        # encoded as a scalar-less Value{tag, metadata-free tensor} is
        # complex; TB renders text via tensor summaries — log as a tagged
        # scalar event count plus keep the text in a sidecar file
        side = self._path + ".text"
        with open(side, "a") as f:
            f.write(f"{step}\t{tag}\t{text_string}\n")

    def flush(self):
        self._f.flush()

    def close(self):
        try:
            self._f.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# torch.utils.tensorboard-shaped alias
SummaryWriter = LogWriter
