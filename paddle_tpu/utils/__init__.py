"""paddle.utils parity (unique_name, deprecated, try_import, dlpack,
cpp_extension, download).

Reference parity: python/paddle/utils/ — the pieces user code commonly
touches. `download` is gated (zero-egress environments); `cpp_extension`
is a real JIT C-extension builder over the baked toolchain (see
cpp_extension.py); the in-tree native runtime itself lives in csrc/.
"""
from __future__ import annotations

import importlib
import warnings

from . import unique_name
from . import dlpack

__all__ = ["unique_name", "deprecated", "try_import", "run_check",
           "download", "dlpack", "cpp_extension"]


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator mirroring paddle.utils.deprecated."""
    def deco(fn):
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__name__} is deprecated since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        return wrapper
    return deco


def try_import(module_name, err_msg=None):
    """paddle.utils.try_import parity."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
            "(this sandbox forbids pip install; gate the feature)")


def run_check():
    """paddle.utils.run_check parity: verify the backend works."""
    import jax
    import jax.numpy as jnp
    n = len(jax.devices())
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 128.0
    print(f"PaddleTPU works well on {n} {jax.default_backend()} "
          f"device{'s' if n > 1 else ''}.")


def require_version(min_version, max_version=None):
    """Parity: paddle.utils.require_version — validates against this
    package's version string."""
    from ..version import full_version

    def parse(v):
        return [int(x) for x in str(v).split(".")[:3] if x.isdigit()]

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > allowed {max_version}")


from . import download  # noqa: E402  (zero-egress-aware cache resolver)
from . import cpp_extension  # noqa: E402  (JIT C-extension builder)
# legacy paddle.utils.profiler namespace -> the real profiler module
from .. import profiler  # noqa: E402
