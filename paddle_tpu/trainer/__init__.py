"""Pretraining Trainer: the north-star training loop (SURVEY.md §7 M7).

Reference parity (capability): the PaddleNLP Trainer atop Fleet —
hybrid-parallel train loop with checkpoint/auto-resume, throughput/MFU
logging, and preemption-safe restart. The reference recovers failures by
relaunch-from-checkpoint (fleet elastic, SURVEY.md §5.3); TPU preemption
works the same way, so the loop here is: restore latest → scan steps →
async-checkpoint every save_steps → on SIGTERM checkpoint and exit 0 so
`paddle_tpu.distributed.launch` (or the TPU pod scheduler) restarts us.
"""
from __future__ import annotations

import hashlib
import math
import os
import signal
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .. import observability as _obs
from ..framework import faults as _faults
from ..framework.flags import flag_value as _fv

__all__ = ["TrainingArguments", "Trainer", "SpeedMeter",
           "device_peak_flops", "AnomalousTrainingError"]


class AnomalousTrainingError(RuntimeError):
    """Training aborted: FLAGS_max_anomalous_steps consecutive NaN/Inf
    or loss-spike steps (docs/ROBUSTNESS.md). The last verified
    checkpoint is intact — anomalous steps are never checkpointed."""


def device_peak_flops(dtype: str = "bfloat16") -> float:
    """Peak FLOP/s of one local accelerator chip, for MFU accounting.
    Known TPU generations by device_kind; conservative 1e12 fallback."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    table = {  # bf16 peak per chip
        "tpu v4": 275e12, "tpu v5 lite": 197e12, "tpu v5e": 197e12,
        "tpu v5p": 459e12, "tpu v5": 459e12, "tpu v6e": 918e12,
        "tpu v6 lite": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v if dtype in ("bfloat16", "float16") else v / 2
    return 1e12


@dataclass
class SpeedMeter:
    """Rolling tokens/sec + MFU meter (the reference reports ips/tokens-per
    -sec per rank; MFU = achieved/(peak) with 6*N FLOPs per token)."""
    n_params: int
    n_devices: int = 1
    dtype: str = "bfloat16"
    window: int = 20
    _times: list = field(default_factory=list)
    _tokens: list = field(default_factory=list)

    def update(self, tokens: int):
        now = time.perf_counter()
        self._times.append(now)
        self._tokens.append(tokens)
        if len(self._times) > self.window + 1:
            self._times.pop(0)
            self._tokens.pop(0)

    @property
    def tokens_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        dt = self._times[-1] - self._times[0]
        return sum(self._tokens[1:]) / dt if dt > 0 else 0.0

    @property
    def mfu(self) -> float:
        peak = device_peak_flops(self.dtype) * self.n_devices
        return (6.0 * self.n_params * self.tokens_per_sec) / peak


@dataclass
class TrainingArguments:
    """Knob bag (parity-shaped with PaddleNLP TrainingArguments; only the
    fields the loop consumes — unknown knobs belong in DistributedStrategy)."""
    output_dir: str = "output"
    max_steps: int = 1000
    logging_steps: int = 10
    save_steps: int = 100
    seed: int = 42
    bf16: bool = False
    max_checkpoints: int = 3
    # hybrid parallel degrees (compiled to mesh axes by fleet)
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_stage: int = 0  # 0=off, 1/2/3 = ZeRO stage
    sep_degree: int = 1      # context/sequence parallel


class Trainer:
    """Minimal-surface pretrain loop over TrainStep/DistTrainStep.

    train() returns a dict with final step/loss and speed stats. Resume is
    automatic: if output_dir holds a checkpoint, training continues from it
    (parity: Trainer resume_from_checkpoint=True by default under elastic).
    """

    def __init__(self, model, optimizer, loss_fn: Callable,
                 args: TrainingArguments, data_iter_fn: Callable,
                 tokens_per_batch: Optional[int] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.args = args
        self.data_iter_fn = data_iter_fn  # (start_step) -> iterator of batches
        self.tokens_per_batch = tokens_per_batch
        self._preempted = False
        self._step_obj = None
        self._ckpt = None

        distributed = (args.dp_degree * args.mp_degree * args.pp_degree *
                       args.sep_degree > 1 or args.sharding_stage >= 2)
        if distributed:
            from ..distributed import fleet
            from ..distributed.fleet import fleet_api
            if fleet_api._fleet_state["hcg"] is None:  # unless user init'd
                strategy = fleet.DistributedStrategy()
                strategy.hybrid_configs = {
                    "dp_degree": args.dp_degree,
                    "mp_degree": args.mp_degree,
                    "pp_degree": args.pp_degree,
                    "sep_degree": args.sep_degree,
                }
                fleet.init(is_collective=True, strategy=strategy)
            from ..distributed.fleet.dist_step import DistTrainStep
            self._step_obj = DistTrainStep(
                model, optimizer, loss_fn,
                sharding_stage=args.sharding_stage)
        else:
            from ..jit.bridge import TrainStep
            self._step_obj = TrainStep(model, optimizer, loss_fn)

    # ------------------------------------------------------- checkpointing --
    def _ckpt_mgr(self):
        if self._ckpt is None:
            from ..distributed.checkpoint import VerifiedCheckpointer
            self._ckpt = VerifiedCheckpointer(
                os.path.join(self.args.output_dir, "checkpoints"),
                max_to_keep=self.args.max_checkpoints,
                async_save=bool(_fv("ckpt_async_save")))
        return self._ckpt

    def _full_state(self, step: int):
        """Model + opt-state + rng as one checkpoint-friendly tree. The
        opt state lives in the compiled step object (donated buffers);
        model params track it after every step, so state_dict() is
        current."""
        state = {"model": dict(self.model.state_dict()),
                 "step": np.asarray(step, dtype=np.int64)}
        opt_leaves = jax.tree_util.tree_leaves(self._step_obj.opt_state)
        state["opt"] = {str(i): leaf for i, leaf in enumerate(opt_leaves)}
        return state

    def _opt_fingerprint(self) -> str:
        """Fingerprint of the optimizer state *structure* (treedef plus
        per-leaf shape/dtype). Persisted in the checkpoint manifest:
        opt leaves are stored by flat index, so restoring into a
        different tree would silently mis-restore — the fingerprint
        turns that into a hard, attributable error."""
        leaves, treedef = jax.tree_util.tree_flatten(
            self._step_obj.opt_state)
        desc = "|".join(
            [str(treedef)]
            + [f"{tuple(np.shape(l))}:{getattr(l, 'dtype', type(l))}"
               for l in leaves])
        return hashlib.sha256(desc.encode()).hexdigest()[:16]

    def _save(self, step: int):
        self._ckpt_mgr().save(step, self._full_state(step),
                              meta={"opt_treedef": self._opt_fingerprint()})

    def _try_resume(self) -> int:
        res = self._ckpt_mgr().restore_latest()
        if res is None:
            return 0
        step, restored, meta = res
        fp, cur = meta.get("opt_treedef"), self._opt_fingerprint()
        if fp is not None and fp != cur:
            raise RuntimeError(
                f"checkpoint step {step} was written with a different "
                f"optimizer state tree (treedef fingerprint {fp} != "
                f"current {cur}): restoring by flat leaf index would "
                "silently mis-restore. Rebuild the Trainer with the "
                "original optimizer configuration, or start fresh with "
                "train(resume=False).")
        # write model params back (jnp.array: force XLA-owned copies —
        # donated buffers must never alias host numpy memory)
        model_sd = self.model.state_dict()
        for k, v in model_sd.items():
            if k in restored["model"]:
                v._value = jnp.array(restored["model"][k])
        # rebuild opt state with the original treedef
        leaves, treedef = jax.tree_util.tree_flatten(self._step_obj.opt_state)
        if len(restored["opt"]) != len(leaves):
            raise RuntimeError(
                f"checkpoint step {step} holds {len(restored['opt'])} "
                f"optimizer leaves but the current optimizer has "
                f"{len(leaves)} — the optimizer changed between runs.")
        new_leaves = [jnp.array(restored["opt"][str(i)])
                      for i in range(len(leaves))]
        self._step_obj._opt_state = jax.tree_util.tree_unflatten(
            treedef, new_leaves)
        return int(np.asarray(restored["step"]))

    # ------------------------------------------------------------ the loop --
    _PREEMPT_SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def _install_preemption_hook(self):
        """SIGTERM/SIGINT -> checkpoint-and-exit at the next step
        boundary. Chains to any pre-existing handler (so an outer
        framework's hook still runs) and records the originals for
        restoration when train() returns — installing a Trainer must
        not permanently clobber the process's signal handling."""
        self._prev_handlers = {}
        self._flight_reason = None

        def handler(signum, frame):
            self._preempted = True  # acted on at the next step boundary
            # crash-time forensics are deferred to that boundary:
            # dumping here would take the flight-recorder/registry
            # locks the interrupted main thread may already hold
            # (non-reentrant -> self-deadlock inside a signal handler)
            self._flight_reason = f"signal_{signum}"
            prev = self._prev_handlers.get(signum)
            if callable(prev) and prev is not signal.default_int_handler:
                prev(signum, frame)  # chain (but not KeyboardInterrupt)

        for s in self._PREEMPT_SIGNALS:
            try:
                self._prev_handlers[s] = signal.signal(s, handler)
            except ValueError:
                pass  # not the main thread (e.g. under a test runner)

    def _restore_preemption_hook(self):
        for s, prev in getattr(self, "_prev_handlers", {}).items():
            if prev is None:
                continue  # non-Python handler: leave as-is
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._prev_handlers = {}

    # -------------------------------------------------------- anomaly guard --
    def _guard_check(self, step: int, loss, parent=None) -> bool:
        """Sync one step's loss and classify it. Returns True when the
        step is anomalous (NaN/Inf, or a spike vs the rolling mean of
        recent good losses). Consecutive anomalies beyond
        FLAGS_max_anomalous_steps abort with AnomalousTrainingError.
        Called at most once per step (the `nan_loss` fault site is
        consumed here, one check per step)."""
        with _obs.span("train.loss_sync", parent=parent, step=step + 1):
            lv = float(loss)
        fa = _faults.check("nan_loss", step=step)
        if fa is not None:
            lv = float("inf") if fa.mode == "inf" else float("nan")
        anomalous, reason = not math.isfinite(lv), "nonfinite"
        spike = float(_fv("loss_spike_factor"))
        window = self._good_losses
        if not anomalous and spike > 0 and len(window) >= 5:
            mean = sum(window) / len(window)
            if abs(lv) > spike * max(abs(mean), 1e-12):
                anomalous, reason = True, "spike"
        if anomalous:
            self._anom_consec += 1
            self._anom_total += 1
            _obs.counter("robustness.anomalies_skipped").inc(reason=reason)
            _obs.start_span("train.anomaly_skip", parent=None,
                            step=step + 1, reason=reason,
                            consecutive=self._anom_consec).end()
            self._log({"anomalous_step": step + 1, "loss": lv,
                       "reason": reason,
                       "consecutive": self._anom_consec})
            limit = int(_fv("max_anomalous_steps"))
            if self._anom_consec >= limit:
                try:  # drain in-flight saves so the cited fallback step
                    # is accurate (bounded, best-effort: this path is
                    # already fatal and a parked drain error of ANY kind
                    # must not replace the AnomalousTrainingError)
                    self._ckpt_mgr().wait(timeout_s=5.0)
                except Exception:
                    pass
                last_ok = self._ckpt_mgr().latest_verified()
                _obs.flight_dump(reason="anomalous_training")
                raise AnomalousTrainingError(
                    f"aborting after {self._anom_consec} consecutive "
                    f"anomalous steps (last loss {lv!r} at step "
                    f"{step + 1}, reason {reason}); the newest verified "
                    f"checkpoint is step {last_ok} — anomalous steps "
                    "were never checkpointed. Lower the learning rate, "
                    "inspect the data at this step range, or raise "
                    "FLAGS_max_anomalous_steps.")
        else:
            self._anom_consec = 0
            window.append(lv)
        return anomalous

    def train(self, resume: bool = True):
        args = self.args
        os.makedirs(args.output_dir, exist_ok=True)
        self._install_preemption_hook()
        # per-rank liveness: under the elastic launcher every worker
        # beats into its own PADDLE_RANK_HEARTBEAT file; the launcher's
        # stale-heartbeat detector reads silence there as a wedged rank
        self._hb = None
        hb_path = os.environ.get("PADDLE_RANK_HEARTBEAT")
        if hb_path:
            from ..observability import RankHeartbeat
            self._hb = RankHeartbeat(hb_path, interval=float(
                os.environ.get("PADDLE_RANK_HEARTBEAT_INTERVAL", "1.0")))
            self._hb_rank = os.environ.get(
                "RANK", os.environ.get("PADDLE_TRAINER_ID", "0"))
            self._hb.beat(phase="init", rank=self._hb_rank)
        try:
            return self._train_loop(resume)
        finally:
            if self._hb is not None:
                self._hb.close()
            self._restore_preemption_hook()

    def _train_loop(self, resume: bool):
        args = self.args
        start_step = self._try_resume() if resume else 0
        if self._hb is not None:
            # the resume marker: tools/trace_report.py --recovery ends
            # the incident timeline at this beat
            self._hb.beat(force=True, phase="resumed", step=start_step,
                          rank=self._hb_rank)
        guard = bool(_fv("anomaly_guard"))
        self._anom_consec = 0
        self._anom_total = 0
        self._good_losses = deque(maxlen=20)

        meter = SpeedMeter(
            n_params=sum(int(np.prod(p.shape))
                         for p in self.model.parameters()),
            n_devices=jax.device_count(),
            dtype="bfloat16" if args.bf16 else "float32")
        logs = []
        step = start_step
        loss = None
        loss_val = float("nan")
        save_owed = False       # a save boundary fell on an anomalous step
        pending = None          # (step, loss) awaiting its guard check
        data = self.data_iter_fn(start_step)
        t_start = time.perf_counter()
        for step in range(start_step, args.max_steps):
            # step phase spans (data/dispatch/loss-sync/anomaly-skip):
            # one trace per step, reconstructable as a waterfall by
            # tools/trace_report.py. All no-ops when telemetry is off.
            st_sp = _obs.start_span("train.step", parent=None,
                                    step=step + 1)
            if self._hb is not None:
                self._hb.beat(phase="step", step=step + 1,
                              rank=self._hb_rank)
            fa = _faults.check("slow_step", step=step)
            if fa is not None:
                time.sleep(float(fa.params.get("sleep", 0.05)))
            fa = _faults.check("slow_rank", step=step)
            if fa is not None:
                # per-step straggler injection on ONE rank: with a
                # rank=K param only that rank pays the sleep (the spec
                # is armed fleet-wide through one shared env). The
                # sleep runs inside its own child span so the fleet
                # aggregator's dominant-span diagnosis names it.
                target = fa.params.get("rank")
                if target is None or int(target) == self._env_rank():
                    with _obs.span("train.straggle", parent=st_sp,
                                   step=step + 1):
                        time.sleep(float(fa.params.get("sleep", 0.25)))
            fa = _faults.check("rank_hang", step=step)
            if fa is not None:
                # deliberately wedge: an alive pid whose heartbeat/log
                # go silent — the launcher's stale-heartbeat detector
                # must notice and SIGKILL this rank into a restart
                time.sleep(float(fa.params.get("sleep", 600.0)))
            # rank_slow: persistent MULTIPLICATIVE inflation on one
            # rank — the checked-on-every-rank / paid-on-one pattern of
            # slow_rank, but scaled to the step's measured work
            # (factor=F pays (F-1)x the data+dispatch wall) so it
            # models a degraded host rather than a fixed stall. The
            # mitigation actuator (distributed.launch.mitigate) exists
            # to evict exactly this.
            fa = _faults.check("rank_slow", step=step)
            rank_slow = fa if fa is not None and (
                fa.params.get("rank") is None
                or int(fa.params["rank"]) == self._env_rank()) else None
            t_work0 = time.perf_counter() if rank_slow is not None \
                else 0.0
            with _obs.span("train.data", parent=st_sp, step=step + 1):
                batch = next(data)
            if not isinstance(batch, (tuple, list)):
                batch = (batch,)
            with _obs.span("train.dispatch", parent=st_sp,
                           step=step + 1):
                loss = self._step_obj(*batch)
            if rank_slow is not None:
                factor = float(rank_slow.params.get("factor", 3.0))
                pad = max(0.0, factor - 1.0) \
                    * (time.perf_counter() - t_work0)
                pad = max(pad, float(rank_slow.params.get("min_s",
                                                          0.0)))
                with _obs.span("train.straggle", parent=st_sp,
                               step=step + 1):
                    time.sleep(pad)
            if _faults.check("sigterm", step=step) is not None:
                os.kill(os.getpid(), signal.SIGTERM)  # -> preemption hook
            if self.tokens_per_batch:
                meter.update(self.tokens_per_batch)
            log_b = (step + 1) % args.logging_steps == 0 or self._preempted
            save_b = (step + 1) % args.save_steps == 0 or self._preempted
            last_b = step == args.max_steps - 1
            step_anom = False
            if guard:
                # pipelined check: the previous step's loss syncs only
                # after this step is dispatched, so the guard does not
                # serialize the dispatch queue; boundaries (log/save/
                # preempt/last) check the current step immediately
                if pending is not None:
                    ps, pl = pending
                    pending = None
                    self._guard_check(ps, pl, parent=st_sp)
                if log_b or save_b or last_b:
                    step_anom = self._guard_check(step, loss,
                                                  parent=st_sp)
                else:
                    pending = (step, loss)
            if log_b:
                if guard:
                    # the boundary guard check above already synced this
                    # step's loss; a second span would double-count the
                    # site for a free host read
                    loss_val = float(loss)
                else:
                    with _obs.span("train.loss_sync", parent=st_sp,
                                   step=step + 1):
                        loss_val = float(loss)  # sync at log boundary only
                rec = {"step": step + 1, "loss": round(loss_val, 6),
                       "tokens_per_sec": round(meter.tokens_per_sec, 2),
                       "mfu": round(meter.mfu, 4)}
                logs.append(rec)
                self._log(rec)
                if _obs.enabled():
                    # per-step series come from the step object; the
                    # loop owns loss (synced only at log boundaries)
                    if math.isfinite(loss_val):
                        _obs.gauge("train.loss").set(loss_val)
                    executed = step + 1 - start_step
                    _obs.gauge("robustness.goodput").set(
                        (executed - self._anom_total)
                        / max(executed, 1))
                    if getattr(self._step_obj, "_obs", None) is None:
                        # uninstrumented step (single-device TrainStep):
                        # the loop is the only flusher. Instrumented
                        # steps export per step already — a second flush
                        # here would duplicate snapshots.
                        _obs.maybe_export(step=step + 1)
            if step_anom and save_b:
                # never checkpoint an anomalous step: the save is owed
                # and lands at the next verified-good step
                save_owed = True
                self._log({"checkpoint_skipped_at": step + 1,
                           "reason": "anomalous_step"})
            elif (save_b or (save_owed and guard and not step_anom
                             and pending is None)):
                self._save(step + 1)
                save_owed = False
            st_sp.end(anomalous=step_anom)
            if self._preempted:
                _obs.flight_dump(
                    reason=getattr(self, "_flight_reason", None)
                    or "preempted")
                # just-in-time preemption checkpoint: drain in-flight
                # background saves, but bounded — the scheduler's grace
                # window is finite and a wedged store must not turn a
                # clean preemption into a SIGKILL mid-write
                ddl = float(_fv("ckpt_drain_deadline_s"))
                drained = self._ckpt_mgr().wait(
                    timeout_s=ddl if ddl > 0 else None)
                self._log({"preempted_at": step + 1,
                           "ckpt_drained": drained})
                break
        else:
            step = args.max_steps - 1
            if loss is not None:
                loss_val = float(loss)
        if not self._preempted:   # the preemption path already drained
            self._ckpt_mgr().wait()   # (bounded); don't re-block here
        executed = max(step + 1 - start_step, 1)
        return {"start_step": start_step, "final_step": step + 1,
                "final_loss": loss_val,
                "wall_s": time.perf_counter() - t_start,
                "tokens_per_sec": meter.tokens_per_sec, "mfu": meter.mfu,
                "anomalous_steps": self._anom_total,
                "goodput": (executed - self._anom_total) / executed,
                "preempted": self._preempted, "logs": logs}

    @staticmethod
    def _env_rank() -> int:
        """This worker's global rank under the launcher (0 standalone)."""
        try:
            return int(os.environ.get(
                "RANK", os.environ.get("PADDLE_TRAINER_ID", "0")))
        except ValueError:
            return 0

    def _log(self, rec: dict):
        import logging
        logging.getLogger("paddle_tpu.trainer").info("%s", rec)
