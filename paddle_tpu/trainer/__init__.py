"""Pretraining Trainer: the north-star training loop (SURVEY.md §7 M7).

Reference parity (capability): the PaddleNLP Trainer atop Fleet —
hybrid-parallel train loop with checkpoint/auto-resume, throughput/MFU
logging, and preemption-safe restart. The reference recovers failures by
relaunch-from-checkpoint (fleet elastic, SURVEY.md §5.3); TPU preemption
works the same way, so the loop here is: restore latest → scan steps →
async-checkpoint every save_steps → on SIGTERM checkpoint and exit 0 so
`paddle_tpu.distributed.launch` (or the TPU pod scheduler) restarts us.
"""
from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np
import jax

from ..tensor import Tensor
from .. import observability as _obs

__all__ = ["TrainingArguments", "Trainer", "SpeedMeter",
           "device_peak_flops"]


def device_peak_flops(dtype: str = "bfloat16") -> float:
    """Peak FLOP/s of one local accelerator chip, for MFU accounting.
    Known TPU generations by device_kind; conservative 1e12 fallback."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    table = {  # bf16 peak per chip
        "tpu v4": 275e12, "tpu v5 lite": 197e12, "tpu v5e": 197e12,
        "tpu v5p": 459e12, "tpu v5": 459e12, "tpu v6e": 918e12,
        "tpu v6 lite": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v if dtype in ("bfloat16", "float16") else v / 2
    return 1e12


@dataclass
class SpeedMeter:
    """Rolling tokens/sec + MFU meter (the reference reports ips/tokens-per
    -sec per rank; MFU = achieved/(peak) with 6*N FLOPs per token)."""
    n_params: int
    n_devices: int = 1
    dtype: str = "bfloat16"
    window: int = 20
    _times: list = field(default_factory=list)
    _tokens: list = field(default_factory=list)

    def update(self, tokens: int):
        now = time.perf_counter()
        self._times.append(now)
        self._tokens.append(tokens)
        if len(self._times) > self.window + 1:
            self._times.pop(0)
            self._tokens.pop(0)

    @property
    def tokens_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        dt = self._times[-1] - self._times[0]
        return sum(self._tokens[1:]) / dt if dt > 0 else 0.0

    @property
    def mfu(self) -> float:
        peak = device_peak_flops(self.dtype) * self.n_devices
        return (6.0 * self.n_params * self.tokens_per_sec) / peak


@dataclass
class TrainingArguments:
    """Knob bag (parity-shaped with PaddleNLP TrainingArguments; only the
    fields the loop consumes — unknown knobs belong in DistributedStrategy)."""
    output_dir: str = "output"
    max_steps: int = 1000
    logging_steps: int = 10
    save_steps: int = 100
    seed: int = 42
    bf16: bool = False
    max_checkpoints: int = 3
    # hybrid parallel degrees (compiled to mesh axes by fleet)
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_stage: int = 0  # 0=off, 1/2/3 = ZeRO stage
    sep_degree: int = 1      # context/sequence parallel


class Trainer:
    """Minimal-surface pretrain loop over TrainStep/DistTrainStep.

    train() returns a dict with final step/loss and speed stats. Resume is
    automatic: if output_dir holds a checkpoint, training continues from it
    (parity: Trainer resume_from_checkpoint=True by default under elastic).
    """

    def __init__(self, model, optimizer, loss_fn: Callable,
                 args: TrainingArguments, data_iter_fn: Callable,
                 tokens_per_batch: Optional[int] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.args = args
        self.data_iter_fn = data_iter_fn  # (start_step) -> iterator of batches
        self.tokens_per_batch = tokens_per_batch
        self._preempted = False
        self._step_obj = None
        self._ckpt = None

        distributed = (args.dp_degree * args.mp_degree * args.pp_degree *
                       args.sep_degree > 1 or args.sharding_stage >= 2)
        if distributed:
            from ..distributed import fleet
            from ..distributed.fleet import fleet_api
            if fleet_api._fleet_state["hcg"] is None:  # unless user init'd
                strategy = fleet.DistributedStrategy()
                strategy.hybrid_configs = {
                    "dp_degree": args.dp_degree,
                    "mp_degree": args.mp_degree,
                    "pp_degree": args.pp_degree,
                    "sep_degree": args.sep_degree,
                }
                fleet.init(is_collective=True, strategy=strategy)
            from ..distributed.fleet.dist_step import DistTrainStep
            self._step_obj = DistTrainStep(
                model, optimizer, loss_fn,
                sharding_stage=args.sharding_stage)
        else:
            from ..jit.bridge import TrainStep
            self._step_obj = TrainStep(model, optimizer, loss_fn)

    # ------------------------------------------------------- checkpointing --
    def _ckpt_mgr(self):
        if self._ckpt is None:
            from ..distributed.checkpoint import AsyncCheckpointer
            self._ckpt = AsyncCheckpointer(
                os.path.join(self.args.output_dir, "checkpoints"))
        return self._ckpt

    def _full_state(self, step: int):
        """Model + opt-state + rng as one orbax-friendly tree. The opt state
        lives in the compiled step object (donated buffers); model params
        track it after every step, so state_dict() is current."""
        state = {"model": dict(self.model.state_dict()),
                 "step": np.asarray(step, dtype=np.int64)}
        opt_leaves = jax.tree_util.tree_leaves(self._step_obj.opt_state)
        state["opt"] = {str(i): leaf for i, leaf in enumerate(opt_leaves)}
        return state

    def _save(self, step: int):
        self._ckpt_mgr().save(step, self._full_state(step))

    def _try_resume(self) -> int:
        mgr = self._ckpt_mgr()
        template = self._full_state(0)
        from ..distributed.checkpoint import AsyncCheckpointer  # noqa: F401
        step = mgr._mgr.latest_step()
        if step is None:
            return 0
        import orbax.checkpoint as ocp
        from ..distributed.checkpoint import _to_arrays
        restored = mgr._mgr.restore(
            step, args=ocp.args.StandardRestore(_to_arrays(template)))
        # write model params back
        model_sd = self.model.state_dict()
        for k, v in model_sd.items():
            if k in restored["model"]:
                v._value = restored["model"][k]
        # rebuild opt state with the original treedef
        leaves, treedef = jax.tree_util.tree_flatten(self._step_obj.opt_state)
        new_leaves = [restored["opt"][str(i)] for i in range(len(leaves))]
        self._step_obj._opt_state = jax.tree_util.tree_unflatten(
            treedef, new_leaves)
        return int(restored["step"])

    # ------------------------------------------------------------ the loop --
    def _install_preemption_hook(self):
        def handler(signum, frame):
            self._preempted = True  # acted on at the next step boundary
        try:
            signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass  # not the main thread (e.g. under a test runner)

    def train(self, resume: bool = True):
        args = self.args
        os.makedirs(args.output_dir, exist_ok=True)
        self._install_preemption_hook()
        start_step = self._try_resume() if resume else 0

        meter = SpeedMeter(
            n_params=sum(int(np.prod(p.shape))
                         for p in self.model.parameters()),
            n_devices=jax.device_count(),
            dtype="bfloat16" if args.bf16 else "float32")
        logs = []
        step = start_step
        loss = None
        loss_val = float("nan")
        data = self.data_iter_fn(start_step)
        t_start = time.perf_counter()
        for step in range(start_step, args.max_steps):
            batch = next(data)
            if not isinstance(batch, (tuple, list)):
                batch = (batch,)
            loss = self._step_obj(*batch)
            if self.tokens_per_batch:
                meter.update(self.tokens_per_batch)
            if (step + 1) % args.logging_steps == 0 or self._preempted:
                loss_val = float(loss)  # device sync at log boundary only
                rec = {"step": step + 1, "loss": round(loss_val, 6),
                       "tokens_per_sec": round(meter.tokens_per_sec, 2),
                       "mfu": round(meter.mfu, 4)}
                logs.append(rec)
                self._log(rec)
                if _obs.enabled():
                    # per-step series come from the step object; the
                    # loop owns loss (synced only at log boundaries)
                    _obs.gauge("train.loss").set(loss_val)
                    if getattr(self._step_obj, "_obs", None) is None:
                        # uninstrumented step (single-device TrainStep):
                        # the loop is the only flusher. Instrumented
                        # steps export per step already — a second flush
                        # here would duplicate snapshots.
                        _obs.maybe_export(step=step + 1)
            if (step + 1) % args.save_steps == 0 or self._preempted:
                self._save(step + 1)
            if self._preempted:
                self._ckpt_mgr().wait()
                self._log({"preempted_at": step + 1})
                break
        else:
            step = args.max_steps - 1
            if loss is not None:
                loss_val = float(loss)
        self._ckpt_mgr().wait()
        return {"start_step": start_step, "final_step": step + 1,
                "final_loss": loss_val,
                "wall_s": time.perf_counter() - t_start,
                "tokens_per_sec": meter.tokens_per_sec, "mfu": meter.mfu,
                "preempted": self._preempted, "logs": logs}

    def _log(self, rec: dict):
        import logging
        logging.getLogger("paddle_tpu.trainer").info("%s", rec)
