"""paddle.vision.transforms (parity: python/paddle/vision/transforms/) —
numpy/HWC-based preprocessing transforms."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ..tensor import Tensor, to_tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)


def _img_hw(img):
    return img.shape[0], img.shape[1]


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img)
        h, w = _img_hw(arr)
        if isinstance(self.size, int):
            if h < w:
                oh, ow = self.size, int(self.size * w / h)
            else:
                oh, ow = int(self.size * h / w), self.size
        else:
            oh, ow = self.size
        method = {"bilinear": "linear", "nearest": "nearest",
                  "bicubic": "cubic"}[self.interpolation]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                               (oh, ow) + arr.shape[2:], method=method)
        return np.asarray(out).astype(arr.dtype if arr.dtype != np.uint8 else np.uint8)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2))
        h, w = _img_hw(arr)
        th, tw = self.size
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = _img_hw(arr)
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = _img_hw(arr)
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            ar = pyrandom.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = pyrandom.randint(0, h - th)
                j = pyrandom.randint(0, w - tw)
                crop = arr[i:i + th, j:j + tw]
                return self._resize(crop)
        return self._resize(CenterCrop(min(h, w))(arr))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        was_tensor = isinstance(img, Tensor)
        arr = np.asarray(img.numpy() if was_tensor else img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        out = (arr - m) / s
        return to_tensor(out.astype(np.float32)) if was_tensor else out


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return to_tensor(arr.astype(np.float32))


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        return np.clip(arr * alpha, 0, 255).astype(np.asarray(img).dtype)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self._brightness = brightness
        self._contrast = contrast
        self._saturation = saturation
        self._hue = hue

    def _apply_image(self, img):
        # forward references — the photometric transforms are defined
        # below in this module; apply in random order (reference
        # behavior)
        ts = [BrightnessTransform(self._brightness),
              ContrastTransform(self._contrast),
              SaturationTransform(self._saturation),
              HueTransform(self._hue)]
        pyrandom.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(padding, int):
            padding = [padding] * 4
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img)
        p = self.padding
        width = ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2)
        return np.pad(arr, width, constant_values=self.fill)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def to_tensor_fn(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


# --------------------------------------------------------------------------
# photometric functional ops (parity: python/paddle/vision/transforms/
# functional.py — host-side numpy preprocessing, HWC uint8/float)
# --------------------------------------------------------------------------

def _blend(a, b, alpha):
    out = np.asarray(a, np.float32) * alpha + np.asarray(b, np.float32) \
        * (1 - alpha)
    # value range follows the dtype: float images live in [0, 1],
    # integer images in [0, 255] (r5 fuzz find — float inputs were
    # clipped at 255, i.e. never)
    hi = 255 if np.issubdtype(np.asarray(a).dtype, np.integer) else 1.0
    return np.clip(out, 0, hi).astype(np.asarray(a).dtype)


def adjust_brightness(img, brightness_factor):
    return _blend(img, np.zeros_like(np.asarray(img)), brightness_factor)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    mean = to_grayscale(arr).mean()
    return _blend(img, np.full_like(arr, mean), contrast_factor)


def adjust_saturation(img, saturation_factor):
    gray = to_grayscale(np.asarray(img), num_output_channels=3)
    return _blend(img, gray, saturation_factor)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5] — rotate the hue channel in HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img, np.float32) / 255.0
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr[..., :3].max(-1)
    minc = arr[..., :3].min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0)
    dz = np.maximum(d, 1e-12)
    rc, gc, bc = (maxc - r) / dz, (maxc - g) / dz, (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(d == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    # hsv -> rgb
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1) * 255.0
    return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img, np.float32)
    gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
            + arr[..., 2] * 0.114)
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return gray.astype(np.asarray(img).dtype)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    h, w = _img_hw(img)
    oh, ow = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    # round(), not floor: the upstream/torchvision origin convention
    # (differs for odd margins — r5 fuzz find)
    return crop(img, int(round((h - oh) / 2.0)),
                int(round((w - ow) / 2.0)), oh, ow)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def erase(img, i, j, h, w, v, inplace=False):
    """Parity: paddle.vision.transforms.erase."""
    arr = np.asarray(img) if inplace else np.asarray(img).copy()
    arr[i:i + h, j:j + w] = v
    return arr


def _inverse_warp(img, inv_matrix, fill=0):
    """Apply a 3x3 inverse affine/projective map with bilinear sampling
    (HWC numpy; the host-side twin of ops/_sampling.py)."""
    arr = np.asarray(img, np.float32)
    h, w = arr.shape[:2]
    ys, xs = np.mgrid[0:h, 0:w].astype(np.float32)
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=-1) @ np.asarray(
        inv_matrix, np.float32).T
    cx = coords[..., 0] / np.maximum(coords[..., 2], 1e-9)
    cy = coords[..., 1] / np.maximum(coords[..., 2], 1e-9)
    x0, y0 = np.floor(cx).astype(int), np.floor(cy).astype(int)
    valid = (cx >= -1) & (cx <= w) & (cy >= -1) & (cy <= h)

    def g(yi, xi):
        inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = arr[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
        return np.where(inside[..., None], out, fill)

    wx, wy = cx - x0, cy - y0
    out = (g(y0, x0) * ((1 - wy) * (1 - wx))[..., None]
           + g(y0, x0 + 1) * ((1 - wy) * wx)[..., None]
           + g(y0 + 1, x0) * (wy * (1 - wx))[..., None]
           + g(y0 + 1, x0 + 1) * (wy * wx)[..., None])
    out = np.where(valid[..., None], out, fill)
    return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


def _affine_inv(center, angle, translate, scale, shear):
    cx, cy = center
    rot = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in (shear if isinstance(
        shear, (list, tuple)) else (shear, 0.0)))
    # forward: T(translate) C R(angle, shear) S C^-1 ; invert analytically
    a = np.cos(rot - sy) / max(np.cos(sy), 1e-9)
    b = -np.cos(rot - sy) * np.tan(sx) / max(np.cos(sy), 1e-9) \
        - np.sin(rot)
    c = np.sin(rot - sy) / max(np.cos(sy), 1e-9)
    d = -np.sin(rot - sy) * np.tan(sx) / max(np.cos(sy), 1e-9) \
        + np.cos(rot)
    fwd = np.array([[a * scale, b * scale, 0.0],
                    [c * scale, d * scale, 0.0],
                    [0.0, 0.0, 1.0]], np.float32)
    pre = np.array([[1, 0, cx + translate[0]], [0, 1, cy + translate[1]],
                    [0, 0, 1]], np.float32)
    post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float32)
    m = pre @ fwd @ post
    return np.linalg.inv(m)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    h, w = _img_hw(img)
    ctr = center if center is not None else ((w - 1) / 2, (h - 1) / 2)
    return _inverse_warp(img, _affine_inv(ctr, angle, translate, scale,
                                          shear), fill)


def rotate(img, angle, interpolation="bilinear", expand=False, center=None,
           fill=0):
    return affine(img, angle=angle, fill=fill, center=center)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """Projective warp mapping startpoints -> endpoints (4 corners)."""
    a = []
    bvec = []
    for (x, y), (u, v) in zip(endpoints, startpoints):
        a.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        a.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bvec.extend([u, v])
    coeff = np.linalg.solve(np.asarray(a, np.float64),
                            np.asarray(bvec, np.float64))
    inv = np.append(coeff, 1.0).reshape(3, 3)
    return _inverse_warp(img, inv, fill)


# --------------------------------------------------------------------------
# photometric / geometric transform classes
# --------------------------------------------------------------------------

class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(
            img, 1 + pyrandom.uniform(-self.value, self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_saturation(
            img, 1 + pyrandom.uniform(-self.value, self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, (int, float)) else degrees)
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        return rotate(img, pyrandom.uniform(*self.degrees),
                      center=self.center, fill=self.fill)


class RandomErasing(BaseTransform):
    """Parity: paddle.vision.transforms.RandomErasing (Zhong et al.)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        if pyrandom.random() > self.prob:
            return img
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            ar = pyrandom.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = pyrandom.randint(0, h - eh)
                j = pyrandom.randint(0, w - ew)
                v = (np.random.randn(eh, ew, *arr.shape[2:])
                     if self.value == "random" else self.value)
                return erase(arr, i, j, eh, ew, v, self.inplace)
        return img


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, (int, float)) else degrees)
        self.translate, self.scale_rng = translate, scale
        self.shear, self.fill, self.center = shear, fill, center

    def _apply_image(self, img):
        h, w = _img_hw(img)
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0
        if self.translate is not None:
            tx = pyrandom.uniform(-self.translate[0], self.translate[0]) * w
            ty = pyrandom.uniform(-self.translate[1], self.translate[1]) * h
        sc = (pyrandom.uniform(*self.scale_rng)
              if self.scale_rng is not None else 1.0)
        sh = (0.0, 0.0)
        if self.shear is not None:
            srange = ((-self.shear, self.shear)
                      if isinstance(self.shear, (int, float))
                      else self.shear)
            sh = (pyrandom.uniform(*srange[:2]), 0.0)
        return affine(img, angle=angle, translate=(tx, ty), scale=sc,
                      shear=sh, fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.d = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if pyrandom.random() > self.prob:
            return img
        h, w = _img_hw(img)
        dx, dy = self.d * w / 2, self.d * h / 2
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(pyrandom.uniform(0, dx), pyrandom.uniform(0, dy)),
               (w - 1 - pyrandom.uniform(0, dx), pyrandom.uniform(0, dy)),
               (w - 1 - pyrandom.uniform(0, dx),
                h - 1 - pyrandom.uniform(0, dy)),
               (pyrandom.uniform(0, dx), h - 1 - pyrandom.uniform(0, dy))]
        return perspective(img, start, end, fill=self.fill)


# paddle.vision.transforms.functional is a submodule in the reference;
# transforms_functional imports back from this module, which is safe
# here because every functional def is above this line. Registering in
# sys.modules makes ALL upstream import forms work:
#   import paddle.vision.transforms.functional as F
#   from paddle.vision.transforms.functional import resize
#   paddle.vision.transforms.functional.resize(...)
import sys as _sys  # noqa: E402
from . import transforms_functional as functional  # noqa: E402
_sys.modules[__name__ + ".functional"] = functional
