"""paddle.vision.transforms.functional parity — the functional forms of
the transform ops (python/paddle/vision/transforms/functional.py).

The ndarray/PIL path works on HWC images (the reference's numpy
contract). Tensor inputs are CHW (the reference's tensor contract) and
return Tensors — r5 fuzz find: CHW Tensors were being cropped/flipped
along the wrong axes when handed to the HWC implementations."""
from __future__ import annotations

import functools

import numpy as np

from . import transforms as _T
from .transforms import to_tensor_fn as to_tensor  # noqa: F401


def _wrap_chw(fn):
    """Adapt an HWC-ndarray transform to accept CHW Tensors. Only 3-D
    image Tensors are accepted — paddle's functional rejects batched
    tensors, and passing one through the HWC path would silently
    transform the wrong axes."""
    @functools.wraps(fn)
    def wrapped(img, *args, **kwargs):
        from ..tensor import Tensor
        if isinstance(img, Tensor):
            arr = np.asarray(img.numpy())
            if arr.ndim != 3:
                raise ValueError(
                    f"{fn.__name__}: Tensor images must be 3-D CHW, got "
                    f"shape {tuple(arr.shape)} (apply per image for "
                    "batches)")
            out = fn(arr.transpose(1, 2, 0), *args, **kwargs)
            if isinstance(out, np.ndarray) and out.ndim == 3:
                out = out.transpose(2, 0, 1)
            return Tensor(np.ascontiguousarray(out))
        return fn(img, *args, **kwargs)
    return wrapped


resize = _wrap_chw(_T.resize)
hflip = _wrap_chw(_T.hflip)
vflip = _wrap_chw(_T.vflip)
adjust_brightness = _wrap_chw(_T.adjust_brightness)
adjust_contrast = _wrap_chw(_T.adjust_contrast)
adjust_saturation = _wrap_chw(_T.adjust_saturation)
adjust_hue = _wrap_chw(_T.adjust_hue)
to_grayscale = _wrap_chw(_T.to_grayscale)
crop = _wrap_chw(_T.crop)
center_crop = _wrap_chw(_T.center_crop)
pad = _wrap_chw(_T.pad)
affine = _wrap_chw(_T.affine)
rotate = _wrap_chw(_T.rotate)
perspective = _wrap_chw(_T.perspective)


def erase(img, i, j, h, w, v, inplace=False):
    """CHW Tensors erase in their native layout with a (C, h, w) value
    (the upstream tensor contract — the HWC adapter would transpose the
    region but not `v`); ndarray/PIL inputs use the HWC path."""
    from ..tensor import Tensor
    if isinstance(img, Tensor):
        arr = np.asarray(img.numpy()).copy()
        val = np.asarray(v.numpy() if isinstance(v, Tensor) else v)
        arr[..., i:i + h, j:j + w] = val
        out = Tensor(arr)
        if inplace:
            img._inplace_update(out)
            return img
        return out
    return _T.erase(img, i, j, h, w, v, inplace)


# Normalize handles Tensor inputs and data_format natively
normalize = _T.normalize


__all__ = ["normalize", "resize", "hflip", "vflip", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue",
           "to_grayscale", "crop", "center_crop", "pad", "erase",
           "affine", "rotate", "perspective", "to_tensor"]
