"""paddle.vision.transforms.functional parity — the functional forms of
the transform ops (python/paddle/vision/transforms/functional.py). Thin
re-exports of the implementations in transforms.py with the reference's
public names."""
from __future__ import annotations

from .transforms import (  # noqa: F401
    normalize, resize, hflip, vflip, adjust_brightness, adjust_contrast,
    adjust_saturation, adjust_hue, to_grayscale, crop, center_crop, pad,
    erase, affine, rotate, perspective,
)
from .transforms import to_tensor_fn as to_tensor  # noqa: F401

__all__ = ["normalize", "resize", "hflip", "vflip", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue",
           "to_grayscale", "crop", "center_crop", "pad", "erase",
           "affine", "rotate", "perspective", "to_tensor"]
