"""paddle.vision.ops — detection/vision operators.

Reference parity: python/paddle/vision/ops.py (nms, roi_align, roi_pool,
box_coder, deform_conv2d, psroi_pool, yolo_box, prior_box,
distribute_fpn_proposals). TPU-native design notes:

- roi_align / roi_pool / deform_conv2d / yolo_box / prior_box /
  box_coder are static-shape gather/compute pipelines — fully jittable,
  XLA fuses the gathers (replaces the per-op CUDA kernels in
  paddle/phi/kernels/gpu/).
- nms / distribute_fpn_proposals return data-dependent shapes. On TPU
  the compiled path must be static, so the greedy suppression mask is
  computed with a fixed-trip-count lax loop (jittable); the final
  index extraction happens eagerly (matches how the reference's
  dynamic-shape ops are host-synchronizing on GPU too).
"""
from __future__ import annotations

import math as pymath

import numpy as np
import jax
import jax.numpy as jnp

from ..ops._dispatch import apply
from ..ops.creation import _coerce
from ..tensor import Tensor

__all__ = [
    "nms", "roi_align", "roi_pool", "psroi_pool", "box_coder",
    "deform_conv2d", "yolo_box", "prior_box", "distribute_fpn_proposals",
]


def _iou_matrix(boxes):
    """Pairwise IoU of [N, 4] x1y1x2y2 boxes."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_keep_mask(boxes, scores, iou_threshold):
    """Greedy NMS as a fixed-trip-count suppression loop — jittable."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_matrix(b)

    def body(i, keep):
        # suppress j>i iff kept(i) and iou(i, j) > thr
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # scatter back to original order
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Parity: python/paddle/vision/ops.py nms. Returns kept indices
    (descending score order when scores given). Output length is
    data-dependent, so the index extraction is eager; the O(N^2)
    suppression itself is compiled."""
    boxes_t = _coerce(boxes)
    bj = jnp.asarray(boxes_t._value)
    n = bj.shape[0]
    sj = (jnp.asarray(_coerce(scores)._value) if scores is not None
          else jnp.zeros((n,), bj.dtype))
    if category_idxs is not None:
        cat = jnp.asarray(_coerce(category_idxs)._value)
        # category-aware: offset boxes per category so cross-category
        # pairs never overlap (standard batched-NMS trick)
        span = jnp.max(bj) - jnp.min(bj) + 1.0
        off = cat.astype(bj.dtype)[:, None] * span
        keep = _nms_keep_mask(bj + off, sj, iou_threshold)
    else:
        keep = _nms_keep_mask(bj, sj, iou_threshold)
    idx = np.nonzero(np.asarray(keep))[0]
    s_np = np.asarray(sj)
    idx = idx[np.argsort(-s_np[idx], kind="stable")]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(jnp.asarray(idx, jnp.int64))


from ..ops._sampling import (bilinear_zeros as _roi_bilinear,
                             bilinear_clamped as _roi_bilinear_clamped)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Parity: python/paddle/vision/ops.py roi_align (upstream phi
    roi_align kernel). Static shapes: [num_rois, C, ph, pw].

    sampling_ratio<=0 (adaptive): the reference picks
    ceil(roi_size/pooled_size) per roi; XLA needs one static grid, so
    the batch max is used (denser-but-uniform sampling — identical for
    equal-size rois, slightly denser than the reference for smaller
    ones); under a trace the grid is fixed at 2x2."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    if sampling_ratio > 0:
        sr = int(sampling_ratio)
    else:
        # reference adaptive mode: ceil(roi_size / pooled_size) per roi.
        # Shapes must be static under XLA, so take the max over the batch
        # when roi values are concrete (eager — the reference's dynamic
        # kernel host-syncs here too); under a trace fall back to 2.
        sr = 2
        rv = getattr(_coerce(boxes), "_value", None)
        if rv is not None and not isinstance(rv, jax.core.Tracer):
            rn = np.asarray(rv)
            if rn.size:
                hs = (rn[:, 3] - rn[:, 1]) * spatial_scale / ph
                ws = (rn[:, 2] - rn[:, 0]) * spatial_scale / pw
                sr = max(1, int(np.ceil(max(hs.max(), ws.max()))))

    def fn(v, rois, rois_num):
        n, c, h, w = v.shape
        # map each roi to its batch image
        counts = rois_num.astype(jnp.int32)
        img_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                             total_repeat_length=rois.shape[0])
        offset = 0.5 if aligned else 0.0
        rx1 = rois[:, 0] * spatial_scale - offset
        ry1 = rois[:, 1] * spatial_scale - offset
        rx2 = rois[:, 2] * spatial_scale - offset
        ry2 = rois[:, 3] * spatial_scale - offset
        rw = rx2 - rx1
        rh = ry2 - ry1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sampling grid: sr x sr points per bin
        gy = (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr
              ).reshape(-1)                                # [ph*sr]
        gx = (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr
              ).reshape(-1)                                # [pw*sr]

        def one_roi(ri):
            ys = ry1[ri] + gy * bin_h[ri]                  # [ph*sr]
            xs = rx1[ri] + gx * bin_w[ri]                  # [pw*sr]
            yy = jnp.repeat(ys, pw * sr)
            xx = jnp.tile(xs, ph * sr)
            samp = _roi_bilinear_clamped(v[img_idx[ri]], yy, xx)  # [C, ...]
            samp = samp.reshape(c, ph, sr, pw, sr)
            return samp.mean(axis=(2, 4))                  # [C, ph, pw]

        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply(fn, _coerce(x), _coerce(boxes), _coerce(boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Parity: python/paddle/vision/ops.py roi_pool (max pooling within
    quantized roi bins; upstream phi roi_pool kernel)."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))

    def fn(v, rois, rois_num):
        n, c, h, w = v.shape
        counts = rois_num.astype(jnp.int32)
        img_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                             total_repeat_length=rois.shape[0])
        rx1 = jnp.round(rois[:, 0] * spatial_scale).astype(jnp.int32)
        ry1 = jnp.round(rois[:, 1] * spatial_scale).astype(jnp.int32)
        rx2 = jnp.round(rois[:, 2] * spatial_scale).astype(jnp.int32)
        ry2 = jnp.round(rois[:, 3] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(rx2 - rx1 + 1, 1)
        rh = jnp.maximum(ry2 - ry1 + 1, 1)

        ii = jnp.arange(h)
        jj = jnp.arange(w)

        def one_roi(ri):
            fm = v[img_idx[ri]]                            # [C, H, W]
            # bin (i, j) covers rows [ry1 + floor(i*rh/ph),
            # ry1 + ceil((i+1)*rh/ph)) — overlapping boundary pixels
            # belong to BOTH bins (reference roi_pool semantics)
            bi = jnp.arange(ph)
            bj = jnp.arange(pw)
            ys = ry1[ri] + jnp.floor(bi * rh[ri] / ph).astype(jnp.int32)
            ye = ry1[ri] + jnp.ceil((bi + 1) * rh[ri] / ph).astype(jnp.int32)
            xs = rx1[ri] + jnp.floor(bj * rw[ri] / pw).astype(jnp.int32)
            xe = rx1[ri] + jnp.ceil((bj + 1) * rw[ri] / pw).astype(jnp.int32)
            ymask = ((ii[None, :] >= ys[:, None])
                     & (ii[None, :] < ye[:, None])
                     & (ii[None, :] >= 0))                  # [ph, H]
            xmask = ((jj[None, :] >= xs[:, None])
                     & (jj[None, :] < xe[:, None])
                     & (jj[None, :] >= 0))                  # [pw, W]
            m = ymask[:, None, :, None] & xmask[None, :, None, :]
            neg = jnp.finfo(v.dtype).min
            masked = jnp.where(m[None], fm[:, None, None, :, :], neg)
            pooled = jnp.max(masked, axis=(3, 4))          # [C, ph, pw]
            any_px = jnp.any(m, axis=(2, 3))
            return jnp.where(any_px[None], pooled, 0.0)

        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply(fn, _coerce(x), _coerce(boxes), _coerce(boxes_num))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (parity: python/paddle/vision/ops.py
    psroi_pool): channel block (i,j) feeds output bin (i,j), average
    pooled."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))

    def fn(v, rois, rois_num):
        n, c, h, w = v.shape
        co = c // (ph * pw)
        counts = rois_num.astype(jnp.int32)
        img_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                             total_repeat_length=rois.shape[0])
        rx1 = rois[:, 0] * spatial_scale
        ry1 = rois[:, 1] * spatial_scale
        rw = jnp.maximum(rois[:, 2] - rois[:, 0], 0.1) * spatial_scale
        rh = jnp.maximum(rois[:, 3] - rois[:, 1], 0.1) * spatial_scale
        bh = rh / ph
        bw = rw / pw
        ii = jnp.arange(h)
        jj = jnp.arange(w)

        def one_roi(ri):
            fm = v[img_idx[ri]].reshape(co, ph, pw, h, w)
            ys = ry1[ri] + jnp.arange(ph) * bh[ri]
            ye = ys + bh[ri]
            xs = rx1[ri] + jnp.arange(pw) * bw[ri]
            xe = xs + bw[ri]
            ymask = ((ii[None, :] >= jnp.floor(ys)[:, None])
                     & (ii[None, :] < jnp.ceil(ye)[:, None]))  # [ph, H]
            xmask = ((jj[None, :] >= jnp.floor(xs)[:, None])
                     & (jj[None, :] < jnp.ceil(xe)[:, None]))  # [pw, W]
            m = (ymask[:, None, :, None] & xmask[None, :, None, :]
                 ).astype(fm.dtype)                        # [ph, pw, H, W]
            tot = jnp.einsum("cpqhw,pqhw->cpq", fm, m)
            cnt = jnp.maximum(jnp.sum(m, axis=(2, 3)), 1.0)
            return tot / cnt[None]
        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply(fn, _coerce(x), _coerce(boxes), _coerce(boxes_num))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Parity: python/paddle/vision/ops.py box_coder (upstream phi
    box_coder kernel)."""
    args = [_coerce(prior_box)]
    var_is_tensor = not isinstance(prior_box_var, (list, tuple, float,
                                                   type(None)))
    if var_is_tensor:
        args.append(_coerce(prior_box_var))
    args.append(_coerce(target_box))

    def fn(pb, *rest):
        if var_is_tensor:
            pbv, tb = rest
        else:
            tb = rest[0]
            pbv = (jnp.asarray(prior_box_var, tb.dtype)
                   if prior_box_var is not None else None)
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph_ = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph_ * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
            dw = jnp.log(tw[:, None] / pw[None, :])
            dh = jnp.log(th[:, None] / ph_[None, :])
            out = jnp.stack([dx, dy, dw, dh], axis=-1)  # [T, P, 4]
            if pbv is not None:
                out = out / (pbv if pbv.ndim == 1 else pbv[None, :, :])
            return out
        # decode_center_size: tb is [T, P, 4] deltas (or broadcastable)
        if axis == 1:
            pw, ph_, pcx, pcy = (a[:, None] for a in (pw, ph_, pcx, pcy))
        else:
            pw, ph_, pcx, pcy = (a[None, :] for a in (pw, ph_, pcx, pcy))
        d = tb
        if pbv is not None:
            d = d * (pbv if pbv.ndim == 1 else
                     (pbv[None, :, :] if axis == 0 else pbv[:, None, :]))
        ocx = d[..., 0] * pw + pcx
        ocy = d[..., 1] * ph_ + pcy
        ow = jnp.exp(d[..., 2]) * pw
        oh2 = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([ocx - ow * 0.5, ocy - oh2 * 0.5,
                          ocx + ow * 0.5 - norm,
                          ocy + oh2 * 0.5 - norm], axis=-1)
    return apply(fn, *args)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (parity: python/paddle/vision/ops.py
    deform_conv2d; upstream phi deformable_conv kernel). Gather-based:
    build the deformed im2col volume with bilinear sampling, then one
    big matmul — the MXU-friendly formulation."""
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph_, pw_ = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    args = [_coerce(x), _coerce(offset), _coerce(weight)]
    if bias is not None:
        args.append(_coerce(bias))
    if mask is not None:
        args.append(_coerce(mask))

    def fn(v, off, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        mk = rest.pop(0) if mask is not None else None
        n, c, h, wd = v.shape
        co, cig, kh, kw = w.shape
        ho = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        wo = (wd + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        dg = deformable_groups
        # base sampling positions [kh, kw, ho, wo]
        by = (jnp.arange(ho)[None, None, :, None] * sh - ph_
              + jnp.arange(kh)[:, None, None, None] * dh)
        bx = (jnp.arange(wo)[None, None, None, :] * sw - pw_
              + jnp.arange(kw)[None, :, None, None] * dw)
        off = off.reshape(n, dg, kh, kw, 2, ho, wo)
        oy = off[:, :, :, :, 0]
        ox = off[:, :, :, :, 1]
        ys = by[None, None] + oy    # [N, dg, kh, kw, ho, wo]
        xs = bx[None, None] + ox

        def sample_img(img, ys2, xs2):
            # img [C/dg? no: full C split below], coords [kh,kw,ho,wo]
            return _roi_bilinear(img, ys2.reshape(-1), xs2.reshape(-1))

        cg = c // dg

        def one_n(vi, ysi, xsi, mki):
            # vi [C,H,W]; ysi/xsi [dg,kh,kw,ho,wo]
            cols = []
            for g in range(dg):
                img = vi[g * cg:(g + 1) * cg]
                s = sample_img(img, ysi[g], xsi[g])  # [cg, kh*kw*ho*wo]
                s = s.reshape(cg, kh, kw, ho, wo)
                if mki is not None:
                    s = s * mki[g][None]
                cols.append(s)
            return jnp.concatenate(cols, axis=0)     # [C, kh, kw, ho, wo]

        if mk is not None:
            mk_r = mk.reshape(n, dg, kh, kw, ho, wo)
            cols = jax.vmap(one_n)(v, ys, xs, mk_r)
        else:
            cols = jax.vmap(lambda vi, ysi, xsi: one_n(vi, ysi, xsi, None)
                            )(v, ys, xs)
        # grouped conv as one big contraction: out[n,co,ho,wo]
        wr = w.reshape(groups, co // groups, cig, kh, kw)
        out = jnp.einsum(
            "ngcijhw,gocij->ngohw",
            cols.reshape(n, groups, c // groups, kh, kw, ho, wo), wr)
        out = out.reshape(n, co, ho, wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out
    return apply(fn, *args)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output to boxes+scores (parity:
    python/paddle/vision/ops.py yolo_box; upstream phi yolo_box kernel)."""
    na = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(na, 2)

    def fn(v, imgs):
        n, c, h, w = v.shape
        if iou_aware:
            ioup = jax.nn.sigmoid(v[:, :na].reshape(n, na, 1, h, w))
            v = v[:, na:]
        v = v.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        bx = ((jax.nn.sigmoid(v[:, :, 0]) * scale_x_y
               - (scale_x_y - 1) / 2) + gx[None, None, None, :]) / w
        by = ((jax.nn.sigmoid(v[:, :, 1]) * scale_x_y
               - (scale_x_y - 1) / 2) + gy[None, None, :, None]) / h
        aw = jnp.asarray(anc[:, 0])[None, :, None, None]
        ah = jnp.asarray(anc[:, 1])[None, :, None, None]
        bw = jnp.exp(v[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * ah / (h * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4:5])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        probs = jax.nn.sigmoid(v[:, :, 5:]) * conf
        keep = (conf > conf_thresh).astype(v.dtype)
        imgh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [n, na, h, w, 4]
        boxes = boxes * keep[:, :, 0, :, :, None]
        boxes = boxes.reshape(n, -1, 4)
        scores = (probs * keep).transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        return boxes, scores
    return apply(fn, _coerce(x), _coerce(img_size))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (parity: python/paddle/vision/ops.py prior_box)."""
    def fn(v, img):
        h, w = v.shape[2], v.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sh = steps[1] if steps[1] > 0 else ih / h
        sw = steps[0] if steps[0] > 0 else iw / w
        ars = [1.0]
        for ar in aspect_ratios:
            if not any(abs(ar - e) < 1e-6 for e in ars):
                ars.append(float(ar))
                if flip:
                    ars.append(1.0 / float(ar))
        boxes = []
        for ms in min_sizes:
            if min_max_aspect_ratios_order:
                boxes.append((float(ms), float(ms)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    s = pymath.sqrt(ms * mx)
                    boxes.append((s, s))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    boxes.append((ms * pymath.sqrt(ar), ms / pymath.sqrt(ar)))
            else:
                for ar in ars:
                    boxes.append((ms * pymath.sqrt(ar), ms / pymath.sqrt(ar)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    s = pymath.sqrt(ms * mx)
                    boxes.append((s, s))
        bw = jnp.asarray([b[0] for b in boxes], jnp.float32) / iw
        bh = jnp.asarray([b[1] for b in boxes], jnp.float32) / ih
        cx = (jnp.arange(w) + offset) * sw / iw
        cy = (jnp.arange(h) + offset) * sh / ih
        gcx = jnp.broadcast_to(cx[None, :, None], (h, w, len(boxes)))
        gcy = jnp.broadcast_to(cy[:, None, None], (h, w, len(boxes)))
        out = jnp.stack([gcx - bw / 2, gcy - bh / 2,
                         gcx + bw / 2, gcy + bh / 2], axis=-1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               out.shape)
        return out, var
    return apply(fn, _coerce(input), _coerce(image))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels (parity: python/paddle/vision/ops.py
    distribute_fpn_proposals). Output shapes are data-dependent → eager
    index extraction, like the reference's host-synchronizing op."""
    rois_t = _coerce(fpn_rois)
    rois = np.asarray(rois_t._value)
    off = 1.0 if pixel_offset else 0.0
    ws = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    hs = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(ws * hs)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, restore = [], np.empty((rois.shape[0],), np.int64)
    order = []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        order.append(idx)
    order = np.concatenate(order) if order else np.empty((0,), np.int64)
    restore[order] = np.arange(order.shape[0])
    rois_num_per = None
    if rois_num is not None:
        num = np.asarray(_coerce(rois_num)._value)
        batch_of = np.repeat(np.arange(num.shape[0]), num)
        rois_num_per = [
            Tensor(jnp.asarray(np.bincount(
                batch_of[lvl == level], minlength=num.shape[0]
            ).astype(np.int32)))
            for level in range(min_level, max_level + 1)]
    return outs, Tensor(jnp.asarray(restore[:, None])), rois_num_per
