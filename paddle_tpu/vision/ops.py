"""paddle.vision.ops — detection/vision operators.

Reference parity: python/paddle/vision/ops.py (nms, roi_align, roi_pool,
box_coder, deform_conv2d, psroi_pool, yolo_box, prior_box,
distribute_fpn_proposals). TPU-native design notes:

- roi_align / roi_pool / deform_conv2d / yolo_box / prior_box /
  box_coder are static-shape gather/compute pipelines — fully jittable,
  XLA fuses the gathers (replaces the per-op CUDA kernels in
  paddle/phi/kernels/gpu/).
- nms / distribute_fpn_proposals return data-dependent shapes. On TPU
  the compiled path must be static, so the greedy suppression mask is
  computed with a fixed-trip-count lax loop (jittable); the final
  index extraction happens eagerly (matches how the reference's
  dynamic-shape ops are host-synchronizing on GPU too).
"""
from __future__ import annotations

import math as pymath

import numpy as np
import jax
import jax.numpy as jnp

from ..ops._dispatch import apply
from ..ops.creation import _coerce
from ..tensor import Tensor

__all__ = [
    "nms", "roi_align", "roi_pool", "psroi_pool", "box_coder",
    "deform_conv2d", "yolo_box", "prior_box", "distribute_fpn_proposals",
    "matrix_nms", "generate_proposals", "yolo_loss",
    "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D",
    "ConvNormActivation",
]


def _iou_matrix(boxes):
    """Pairwise IoU of [N, 4] x1y1x2y2 boxes."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_keep_mask(boxes, scores, iou_threshold):
    """Greedy NMS as a fixed-trip-count suppression loop — jittable."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_matrix(b)

    def body(i, keep):
        # suppress j>i iff kept(i) and iou(i, j) > thr
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # scatter back to original order
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Parity: python/paddle/vision/ops.py nms. Returns kept indices
    (descending score order when scores given). Output length is
    data-dependent, so the index extraction is eager; the O(N^2)
    suppression itself is compiled."""
    boxes_t = _coerce(boxes)
    bj = jnp.asarray(boxes_t._value)
    n = bj.shape[0]
    sj = (jnp.asarray(_coerce(scores)._value) if scores is not None
          else jnp.zeros((n,), bj.dtype))
    if category_idxs is not None:
        cat = jnp.asarray(_coerce(category_idxs)._value)
        # category-aware: offset boxes per category so cross-category
        # pairs never overlap (standard batched-NMS trick)
        span = jnp.max(bj) - jnp.min(bj) + 1.0
        off = cat.astype(bj.dtype)[:, None] * span
        keep = _nms_keep_mask(bj + off, sj, iou_threshold)
    else:
        keep = _nms_keep_mask(bj, sj, iou_threshold)
    idx = np.nonzero(np.asarray(keep))[0]
    s_np = np.asarray(sj)
    idx = idx[np.argsort(-s_np[idx], kind="stable")]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(jnp.asarray(idx, jnp.int64))


from ..ops._sampling import (bilinear_zeros as _roi_bilinear,
                             bilinear_clamped as _roi_bilinear_clamped)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Parity: python/paddle/vision/ops.py roi_align (upstream phi
    roi_align kernel). Static shapes: [num_rois, C, ph, pw].

    sampling_ratio<=0 (adaptive): the reference picks
    ceil(roi_size/pooled_size) per roi; XLA needs one static grid, so
    the batch max is used (denser-but-uniform sampling — identical for
    equal-size rois, slightly denser than the reference for smaller
    ones); under a trace the grid is fixed at 2x2."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    if sampling_ratio > 0:
        sr = int(sampling_ratio)
    else:
        # reference adaptive mode: ceil(roi_size / pooled_size) per roi.
        # Shapes must be static under XLA, so take the max over the batch
        # when roi values are concrete (eager — the reference's dynamic
        # kernel host-syncs here too); under a trace fall back to 2.
        sr = 2
        rv = getattr(_coerce(boxes), "_value", None)
        if rv is not None and not isinstance(rv, jax.core.Tracer):
            rn = np.asarray(rv)
            if rn.size:
                hs = (rn[:, 3] - rn[:, 1]) * spatial_scale / ph
                ws = (rn[:, 2] - rn[:, 0]) * spatial_scale / pw
                sr = max(1, int(np.ceil(max(hs.max(), ws.max()))))

    def fn(v, rois, rois_num):
        n, c, h, w = v.shape
        # map each roi to its batch image
        counts = rois_num.astype(jnp.int32)
        img_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                             total_repeat_length=rois.shape[0])
        offset = 0.5 if aligned else 0.0
        rx1 = rois[:, 0] * spatial_scale - offset
        ry1 = rois[:, 1] * spatial_scale - offset
        rx2 = rois[:, 2] * spatial_scale - offset
        ry2 = rois[:, 3] * spatial_scale - offset
        rw = rx2 - rx1
        rh = ry2 - ry1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sampling grid: sr x sr points per bin
        gy = (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr
              ).reshape(-1)                                # [ph*sr]
        gx = (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr
              ).reshape(-1)                                # [pw*sr]

        def one_roi(ri):
            ys = ry1[ri] + gy * bin_h[ri]                  # [ph*sr]
            xs = rx1[ri] + gx * bin_w[ri]                  # [pw*sr]
            yy = jnp.repeat(ys, pw * sr)
            xx = jnp.tile(xs, ph * sr)
            samp = _roi_bilinear_clamped(v[img_idx[ri]], yy, xx)  # [C, ...]
            samp = samp.reshape(c, ph, sr, pw, sr)
            return samp.mean(axis=(2, 4))                  # [C, ph, pw]

        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply(fn, _coerce(x), _coerce(boxes), _coerce(boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Parity: python/paddle/vision/ops.py roi_pool (max pooling within
    quantized roi bins; upstream phi roi_pool kernel)."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))

    def fn(v, rois, rois_num):
        n, c, h, w = v.shape
        counts = rois_num.astype(jnp.int32)
        img_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                             total_repeat_length=rois.shape[0])
        rx1 = jnp.round(rois[:, 0] * spatial_scale).astype(jnp.int32)
        ry1 = jnp.round(rois[:, 1] * spatial_scale).astype(jnp.int32)
        rx2 = jnp.round(rois[:, 2] * spatial_scale).astype(jnp.int32)
        ry2 = jnp.round(rois[:, 3] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(rx2 - rx1 + 1, 1)
        rh = jnp.maximum(ry2 - ry1 + 1, 1)

        ii = jnp.arange(h)
        jj = jnp.arange(w)

        def one_roi(ri):
            fm = v[img_idx[ri]]                            # [C, H, W]
            # bin (i, j) covers rows [ry1 + floor(i*rh/ph),
            # ry1 + ceil((i+1)*rh/ph)) — overlapping boundary pixels
            # belong to BOTH bins (reference roi_pool semantics)
            bi = jnp.arange(ph)
            bj = jnp.arange(pw)
            ys = ry1[ri] + jnp.floor(bi * rh[ri] / ph).astype(jnp.int32)
            ye = ry1[ri] + jnp.ceil((bi + 1) * rh[ri] / ph).astype(jnp.int32)
            xs = rx1[ri] + jnp.floor(bj * rw[ri] / pw).astype(jnp.int32)
            xe = rx1[ri] + jnp.ceil((bj + 1) * rw[ri] / pw).astype(jnp.int32)
            ymask = ((ii[None, :] >= ys[:, None])
                     & (ii[None, :] < ye[:, None])
                     & (ii[None, :] >= 0))                  # [ph, H]
            xmask = ((jj[None, :] >= xs[:, None])
                     & (jj[None, :] < xe[:, None])
                     & (jj[None, :] >= 0))                  # [pw, W]
            m = ymask[:, None, :, None] & xmask[None, :, None, :]
            neg = jnp.finfo(v.dtype).min
            masked = jnp.where(m[None], fm[:, None, None, :, :], neg)
            pooled = jnp.max(masked, axis=(3, 4))          # [C, ph, pw]
            any_px = jnp.any(m, axis=(2, 3))
            return jnp.where(any_px[None], pooled, 0.0)

        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply(fn, _coerce(x), _coerce(boxes), _coerce(boxes_num))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (parity: python/paddle/vision/ops.py
    psroi_pool): channel block (i,j) feeds output bin (i,j), average
    pooled."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))

    def fn(v, rois, rois_num):
        n, c, h, w = v.shape
        co = c // (ph * pw)
        counts = rois_num.astype(jnp.int32)
        img_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                             total_repeat_length=rois.shape[0])
        rx1 = rois[:, 0] * spatial_scale
        ry1 = rois[:, 1] * spatial_scale
        rw = jnp.maximum(rois[:, 2] - rois[:, 0], 0.1) * spatial_scale
        rh = jnp.maximum(rois[:, 3] - rois[:, 1], 0.1) * spatial_scale
        bh = rh / ph
        bw = rw / pw
        ii = jnp.arange(h)
        jj = jnp.arange(w)

        def one_roi(ri):
            fm = v[img_idx[ri]].reshape(co, ph, pw, h, w)
            ys = ry1[ri] + jnp.arange(ph) * bh[ri]
            ye = ys + bh[ri]
            xs = rx1[ri] + jnp.arange(pw) * bw[ri]
            xe = xs + bw[ri]
            ymask = ((ii[None, :] >= jnp.floor(ys)[:, None])
                     & (ii[None, :] < jnp.ceil(ye)[:, None]))  # [ph, H]
            xmask = ((jj[None, :] >= jnp.floor(xs)[:, None])
                     & (jj[None, :] < jnp.ceil(xe)[:, None]))  # [pw, W]
            m = (ymask[:, None, :, None] & xmask[None, :, None, :]
                 ).astype(fm.dtype)                        # [ph, pw, H, W]
            tot = jnp.einsum("cpqhw,pqhw->cpq", fm, m)
            cnt = jnp.maximum(jnp.sum(m, axis=(2, 3)), 1.0)
            return tot / cnt[None]
        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply(fn, _coerce(x), _coerce(boxes), _coerce(boxes_num))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Parity: python/paddle/vision/ops.py box_coder (upstream phi
    box_coder kernel)."""
    args = [_coerce(prior_box)]
    var_is_tensor = not isinstance(prior_box_var, (list, tuple, float,
                                                   type(None)))
    if var_is_tensor:
        args.append(_coerce(prior_box_var))
    args.append(_coerce(target_box))

    def fn(pb, *rest):
        if var_is_tensor:
            pbv, tb = rest
        else:
            tb = rest[0]
            pbv = (jnp.asarray(prior_box_var, tb.dtype)
                   if prior_box_var is not None else None)
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph_ = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph_ * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
            dw = jnp.log(tw[:, None] / pw[None, :])
            dh = jnp.log(th[:, None] / ph_[None, :])
            out = jnp.stack([dx, dy, dw, dh], axis=-1)  # [T, P, 4]
            if pbv is not None:
                out = out / (pbv if pbv.ndim == 1 else pbv[None, :, :])
            return out
        # decode_center_size: tb is [T, P, 4] deltas (or broadcastable)
        if axis == 1:
            pw, ph_, pcx, pcy = (a[:, None] for a in (pw, ph_, pcx, pcy))
        else:
            pw, ph_, pcx, pcy = (a[None, :] for a in (pw, ph_, pcx, pcy))
        d = tb
        if pbv is not None:
            d = d * (pbv if pbv.ndim == 1 else
                     (pbv[None, :, :] if axis == 0 else pbv[:, None, :]))
        ocx = d[..., 0] * pw + pcx
        ocy = d[..., 1] * ph_ + pcy
        ow = jnp.exp(d[..., 2]) * pw
        oh2 = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([ocx - ow * 0.5, ocy - oh2 * 0.5,
                          ocx + ow * 0.5 - norm,
                          ocy + oh2 * 0.5 - norm], axis=-1)
    return apply(fn, *args)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (parity: python/paddle/vision/ops.py
    deform_conv2d; upstream phi deformable_conv kernel). Gather-based:
    build the deformed im2col volume with bilinear sampling, then one
    big matmul — the MXU-friendly formulation."""
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph_, pw_ = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    args = [_coerce(x), _coerce(offset), _coerce(weight)]
    if bias is not None:
        args.append(_coerce(bias))
    if mask is not None:
        args.append(_coerce(mask))

    def fn(v, off, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        mk = rest.pop(0) if mask is not None else None
        n, c, h, wd = v.shape
        co, cig, kh, kw = w.shape
        ho = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        wo = (wd + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        dg = deformable_groups
        # base sampling positions [kh, kw, ho, wo]
        by = (jnp.arange(ho)[None, None, :, None] * sh - ph_
              + jnp.arange(kh)[:, None, None, None] * dh)
        bx = (jnp.arange(wo)[None, None, None, :] * sw - pw_
              + jnp.arange(kw)[None, :, None, None] * dw)
        off = off.reshape(n, dg, kh, kw, 2, ho, wo)
        oy = off[:, :, :, :, 0]
        ox = off[:, :, :, :, 1]
        ys = by[None, None] + oy    # [N, dg, kh, kw, ho, wo]
        xs = bx[None, None] + ox

        def sample_img(img, ys2, xs2):
            # img [C/dg? no: full C split below], coords [kh,kw,ho,wo]
            return _roi_bilinear(img, ys2.reshape(-1), xs2.reshape(-1))

        cg = c // dg

        def one_n(vi, ysi, xsi, mki):
            # vi [C,H,W]; ysi/xsi [dg,kh,kw,ho,wo]
            cols = []
            for g in range(dg):
                img = vi[g * cg:(g + 1) * cg]
                s = sample_img(img, ysi[g], xsi[g])  # [cg, kh*kw*ho*wo]
                s = s.reshape(cg, kh, kw, ho, wo)
                if mki is not None:
                    s = s * mki[g][None]
                cols.append(s)
            return jnp.concatenate(cols, axis=0)     # [C, kh, kw, ho, wo]

        if mk is not None:
            mk_r = mk.reshape(n, dg, kh, kw, ho, wo)
            cols = jax.vmap(one_n)(v, ys, xs, mk_r)
        else:
            cols = jax.vmap(lambda vi, ysi, xsi: one_n(vi, ysi, xsi, None)
                            )(v, ys, xs)
        # grouped conv as one big contraction: out[n,co,ho,wo]
        wr = w.reshape(groups, co // groups, cig, kh, kw)
        out = jnp.einsum(
            "ngcijhw,gocij->ngohw",
            cols.reshape(n, groups, c // groups, kh, kw, ho, wo), wr)
        out = out.reshape(n, co, ho, wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out
    return apply(fn, *args)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output to boxes+scores (parity:
    python/paddle/vision/ops.py yolo_box; upstream phi yolo_box kernel)."""
    na = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(na, 2)

    def fn(v, imgs):
        n, c, h, w = v.shape
        if iou_aware:
            ioup = jax.nn.sigmoid(v[:, :na].reshape(n, na, 1, h, w))
            v = v[:, na:]
        v = v.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        bx = ((jax.nn.sigmoid(v[:, :, 0]) * scale_x_y
               - (scale_x_y - 1) / 2) + gx[None, None, None, :]) / w
        by = ((jax.nn.sigmoid(v[:, :, 1]) * scale_x_y
               - (scale_x_y - 1) / 2) + gy[None, None, :, None]) / h
        aw = jnp.asarray(anc[:, 0])[None, :, None, None]
        ah = jnp.asarray(anc[:, 1])[None, :, None, None]
        bw = jnp.exp(v[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * ah / (h * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4:5])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        probs = jax.nn.sigmoid(v[:, :, 5:]) * conf
        keep = (conf > conf_thresh).astype(v.dtype)
        imgh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [n, na, h, w, 4]
        boxes = boxes * keep[:, :, 0, :, :, None]
        boxes = boxes.reshape(n, -1, 4)
        scores = (probs * keep).transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        return boxes, scores
    return apply(fn, _coerce(x), _coerce(img_size))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (parity: python/paddle/vision/ops.py prior_box)."""
    def fn(v, img):
        h, w = v.shape[2], v.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sh = steps[1] if steps[1] > 0 else ih / h
        sw = steps[0] if steps[0] > 0 else iw / w
        ars = [1.0]
        for ar in aspect_ratios:
            if not any(abs(ar - e) < 1e-6 for e in ars):
                ars.append(float(ar))
                if flip:
                    ars.append(1.0 / float(ar))
        boxes = []
        for ms in min_sizes:
            if min_max_aspect_ratios_order:
                boxes.append((float(ms), float(ms)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    s = pymath.sqrt(ms * mx)
                    boxes.append((s, s))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    boxes.append((ms * pymath.sqrt(ar), ms / pymath.sqrt(ar)))
            else:
                for ar in ars:
                    boxes.append((ms * pymath.sqrt(ar), ms / pymath.sqrt(ar)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    s = pymath.sqrt(ms * mx)
                    boxes.append((s, s))
        bw = jnp.asarray([b[0] for b in boxes], jnp.float32) / iw
        bh = jnp.asarray([b[1] for b in boxes], jnp.float32) / ih
        cx = (jnp.arange(w) + offset) * sw / iw
        cy = (jnp.arange(h) + offset) * sh / ih
        gcx = jnp.broadcast_to(cx[None, :, None], (h, w, len(boxes)))
        gcy = jnp.broadcast_to(cy[:, None, None], (h, w, len(boxes)))
        out = jnp.stack([gcx - bw / 2, gcy - bh / 2,
                         gcx + bw / 2, gcy + bh / 2], axis=-1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               out.shape)
        return out, var
    return apply(fn, _coerce(input), _coerce(image))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels (parity: python/paddle/vision/ops.py
    distribute_fpn_proposals). Output shapes are data-dependent → eager
    index extraction, like the reference's host-synchronizing op."""
    rois_t = _coerce(fpn_rois)
    rois = np.asarray(rois_t._value)
    off = 1.0 if pixel_offset else 0.0
    ws = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    hs = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(ws * hs)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, restore = [], np.empty((rois.shape[0],), np.int64)
    order = []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        order.append(idx)
    order = np.concatenate(order) if order else np.empty((0,), np.int64)
    restore[order] = np.arange(order.shape[0])
    rois_num_per = None
    if rois_num is not None:
        num = np.asarray(_coerce(rois_num)._value)
        batch_of = np.repeat(np.arange(num.shape[0]), num)
        rois_num_per = [
            Tensor(jnp.asarray(np.bincount(
                batch_of[lvl == level], minlength=num.shape[0]
            ).astype(np.int32)))
            for level in range(min_level, max_level + 1)]
    return outs, Tensor(jnp.asarray(restore[:, None])), rois_num_per


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): scores decay by IoU overlap instead of hard
    suppression (parity: python/paddle/vision/ops.py matrix_nms; upstream
    phi matrix_nms kernel). bboxes [N, M, 4], scores [N, C, M]. Output
    rows are [label, score, x1, y1, x2, y2]. Data-dependent output size →
    eager extraction like nms/distribute_fpn_proposals above."""
    bb = np.asarray(_coerce(bboxes)._value)
    sc = np.asarray(_coerce(scores)._value)
    n, c, m = sc.shape
    all_rows, all_idx, rois_num = [], [], []
    for b in range(n):
        rows, idxs = [], []
        for cls in range(c):
            if cls == background_label:
                continue
            s = sc[b, cls]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            sel = sel[np.argsort(-s[sel])][:nms_top_k]
            boxes = bb[b, sel]
            ss = s[sel]
            iou = np.asarray(_iou_matrix(jnp.asarray(boxes)))
            k = sel.size
            # decay: for each j, min over higher-scored i of
            # f(iou_ij) / f(iou_cmax_i), iou_cmax_i = i's own max overlap
            tri = np.triu(iou, 1)  # iou of higher-scored i with j (i<j)
            # comp[i] = i's own max overlap from anything above it —
            # j-invariant, computed once (O(k^2) total)
            comp_full = np.array([tri[:i, i].max() if i else 0.0
                                  for i in range(k)])
            decay = np.ones((k,))
            for j in range(1, k):
                ov = tri[:j, j]
                comp = comp_full[:j]
                if use_gaussian:
                    d = np.exp(-(ov ** 2 - comp ** 2) / gaussian_sigma)
                else:
                    d = (1.0 - ov) / np.maximum(1.0 - comp, 1e-10)
                decay[j] = d.min()
            dec = ss * decay
            keep = np.nonzero(dec > post_threshold)[0]
            for j in keep:
                rows.append([float(cls), float(dec[j]), *boxes[j]])
                idxs.append(b * m + int(sel[j]))
        if rows:
            rows = np.asarray(rows, np.float32)
            idxs = np.asarray(idxs, np.int64)
            order = np.argsort(-rows[:, 1])[:keep_top_k]
            rows, idxs = rows[order], idxs[order]
        else:
            rows = np.zeros((0, 6), np.float32)
            idxs = np.zeros((0,), np.int64)
        all_rows.append(rows)
        all_idx.append(idxs)
        rois_num.append(rows.shape[0])
    out = Tensor(jnp.asarray(np.concatenate(all_rows, axis=0)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(
            np.concatenate(all_idx)[:, None])))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True,
                       name=None):
    """RPN proposal generation (parity: python/paddle/vision/ops.py
    generate_proposals; upstream phi generate_proposals_v2). scores
    [N, A, H, W], bbox_deltas [N, 4A, H, W], anchors [H, W, A, 4] (or
    flattened [HWA, 4]), variances like anchors."""
    sc = np.asarray(_coerce(scores)._value)
    bd = np.asarray(_coerce(bbox_deltas)._value)
    ims = np.asarray(_coerce(img_size)._value)
    an = np.asarray(_coerce(anchors)._value).reshape(-1, 4)
    va = np.asarray(_coerce(variances)._value).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for b in range(n):
        s = sc[b].transpose(1, 2, 0).reshape(-1)           # HWA
        d = bd[b].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, anc, var = s[order], d[order], an[order], va[order]
        # decode (x1y1x2y2 anchors; deltas dx dy dw dh scaled by variance)
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        dx, dy, dw, dh = (d * var).T
        cx = dx * aw + acx
        cy = dy * ah + acy
        bw = np.exp(np.minimum(dw, np.log(1000.0 / 16))) * aw
        bh = np.exp(np.minimum(dh, np.log(1000.0 / 16))) * ah
        props = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], axis=1)
        # clip to image, filter small
        im_h, im_w = ims[b]
        props[:, 0::2] = np.clip(props[:, 0::2], 0, im_w - off)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, im_h - off)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        valid = (ws >= min_size) & (hs >= min_size)
        props, s = props[valid], s[valid]
        if props.shape[0]:
            keep = np.asarray(_nms_keep_mask(jnp.asarray(props),
                                             jnp.asarray(s), nms_thresh))
            props, s = props[keep], s[keep]
            order = np.argsort(-s)[:post_nms_top_n]
            props, s = props[order], s[order]
        all_rois.append(props.astype(np.float32))
        all_probs.append(s.astype(np.float32))
        nums.append(props.shape[0])
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, axis=0)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs)[:, None]))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(
            np.asarray(nums, np.int32)))
    return rois, probs


def yolo_loss(x, gt_box, gt_label, anchors, class_num, gt_score=None,
              anchor_mask=None, ignore_thresh=0.7, downsample_ratio=32,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss for one detection head (parity: python/paddle/vision/
    ops.py yolo_loss; upstream phi yolov3_loss kernel). x [N, A*(5+C),
    H, W]; gt_box [N, B, 4] (cx, cy, w, h in image units); gt_label
    [N, B]; anchors flat [a0w, a0h, a1w, ...]; anchor_mask picks this
    head's anchors. Returns per-image loss [N].

    Whole-lattice formulation (no python loop over gt): every gt is
    matched to its best full-anchor-set IoU; matches belonging to this
    head's mask become positives at their grid cell. All terms are
    dense masked reductions — XLA-friendly."""
    anchors = list(anchors)
    if anchor_mask is None:
        anchor_mask = list(range(len(anchors) // 2))
    xm = _coerce(x)
    gb = _coerce(gt_box)
    gl = _coerce(gt_label)
    gs = _coerce(gt_score) if gt_score is not None else None
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    an_mask = np.asarray(anchor_mask, np.int64)

    def fn(xv, gbv, glv, *rest):
        gsv = rest[0] if rest else None
        n, ch, h, w = xv.shape
        na = len(an_mask)
        nc = class_num
        xv = xv.reshape(n, na, 5 + nc, h, w)
        px = jax.nn.sigmoid(xv[:, :, 0]) * scale_x_y \
            - 0.5 * (scale_x_y - 1.0)
        py = jax.nn.sigmoid(xv[:, :, 1]) * scale_x_y \
            - 0.5 * (scale_x_y - 1.0)
        pw, ph = xv[:, :, 2], xv[:, :, 3]
        pobj = xv[:, :, 4]
        pcls = xv[:, :, 5:]

        in_w = w * downsample_ratio
        in_h = h * downsample_ratio
        # gt in grid units
        gx = gbv[..., 0] / in_w * w
        gy = gbv[..., 1] / in_h * h
        gw = gbv[..., 2] / in_w * w
        gh = gbv[..., 3] / in_h * h
        valid = (gbv[..., 2] > 0) & (gbv[..., 3] > 0)     # [N,B]

        # anchor assignment: best IoU at the origin over the FULL set
        aw = an_all[:, 0] / downsample_ratio
        ah = an_all[:, 1] / downsample_ratio
        inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(
            gh[..., None], ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)
        # positive iff the best anchor belongs to this head
        local = jnp.full(an_all.shape[0], -1).at[an_mask].set(
            jnp.arange(na))
        lanch = local[best]                                # [N,B]
        pos = valid & (lanch >= 0)

        ci = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        cj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        tx = gx - ci
        ty = gy - cj
        sel_aw = aw[jnp.clip(best, 0, an_all.shape[0] - 1)]
        sel_ah = ah[jnp.clip(best, 0, an_all.shape[0] - 1)]
        tw = jnp.log(jnp.maximum(gw / sel_aw, 1e-9))
        th = jnp.log(jnp.maximum(gh / sel_ah, 1e-9))
        box_w = 2.0 - gw * gh / (w * h)                    # small-box boost

        bidx = jnp.broadcast_to(jnp.arange(n)[:, None], pos.shape)
        la = jnp.where(pos, lanch, 0)

        def gathered(pred):
            return pred[bidx, la, cj, ci]                  # [N,B]

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target \
                + jnp.log1p(jnp.exp(-jnp.abs(logit)))

        obj_w = (gsv[..., 0] if (gsv is not None and gsv.ndim == 3)
                 else (gsv if gsv is not None else 1.0))
        wpos = jnp.where(pos, 1.0, 0.0) * obj_w
        loss_xy = (bce(xv[:, :, 0][bidx, la, cj, ci], tx)
                   + bce(xv[:, :, 1][bidx, la, cj, ci], ty)) \
            * box_w * wpos
        loss_wh = (jnp.abs(gathered(pw) - tw)
                   + jnp.abs(gathered(ph) - th)) * box_w * wpos

        # objectness: positives -> 1; negatives -> 0 unless their best
        # pred-gt IoU exceeds ignore_thresh
        pred_x = (px + jnp.arange(w))                      # [N,A,H,W]
        pred_y = (py + jnp.arange(h)[:, None])
        head_aw = aw[jnp.asarray(an_mask)][None, :, None, None]
        head_ah = ah[jnp.asarray(an_mask)][None, :, None, None]
        pred_w = jnp.exp(pw) * head_aw
        pred_h = jnp.exp(ph) * head_ah

        def box_iou(px1, py1, px2, py2, qx1, qy1, qx2, qy2):
            ix = jnp.maximum(jnp.minimum(px2, qx2)
                             - jnp.maximum(px1, qx1), 0)
            iy = jnp.maximum(jnp.minimum(py2, qy2)
                             - jnp.maximum(py1, qy1), 0)
            inter = ix * iy
            ua = (px2 - px1) * (py2 - py1) + (qx2 - qx1) * (qy2 - qy1) \
                - inter
            return inter / jnp.maximum(ua, 1e-10)

        # IoU of every prediction with every gt: [N,A,H,W,B]
        iou = box_iou(
            (pred_x - pred_w / 2)[..., None],
            (pred_y - pred_h / 2)[..., None],
            (pred_x + pred_w / 2)[..., None],
            (pred_y + pred_h / 2)[..., None],
            (gx - gw / 2)[:, None, None, None, :],
            (gy - gh / 2)[:, None, None, None, :],
            (gx + gw / 2)[:, None, None, None, :],
            (gy + gh / 2)[:, None, None, None, :])
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = iou.max(axis=-1)
        noobj_mask = best_iou < ignore_thresh

        obj_t = jnp.zeros_like(pobj)
        obj_t = obj_t.at[bidx, la, cj, ci].max(
            jnp.where(pos, 1.0, 0.0))
        is_pos_cell = obj_t > 0
        loss_obj = jnp.where(
            is_pos_cell, bce(pobj, 1.0),
            jnp.where(noobj_mask, bce(pobj, 0.0), 0.0))

        # classification at positive cells
        smooth = 1.0 / max(nc, 1) if use_label_smooth else 0.0
        delta = (1.0 - smooth) if use_label_smooth else 1.0
        cls_t = jax.nn.one_hot(jnp.where(pos, glv, 0), nc) * delta \
            + smooth / max(nc, 1)
        pcls_g = jnp.moveaxis(pcls, 2, -1)[bidx, la, cj, ci]  # [N,B,C]
        loss_cls = jnp.sum(bce(pcls_g, cls_t), axis=-1) * wpos

        per_img = (jnp.sum(loss_xy + loss_wh + loss_cls, axis=1)
                   + jnp.sum(loss_obj, axis=(1, 2, 3)))
        return per_img

    args = [xm, gb, gl] + ([gs] if gs is not None else [])
    return apply(fn, *args, _name="yolo_loss")


# ---------------------------------------------------------------------------
# Layer-class wrappers (parity: python/paddle/vision/ops.py RoIAlign/
# RoIPool/PSRoIPool/DeformConv2D/ConvNormActivation)
# ---------------------------------------------------------------------------

from ..nn.layer_base import Layer as _Layer  # noqa: E402


class RoIAlign(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class DeformConv2D(_Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw], attr=weight_attr)
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self._stride, padding=self._padding,
                             dilation=self._dilation,
                             deformable_groups=self._deformable_groups,
                             groups=self._groups, mask=mask)


class ConvNormActivation(_Layer):
    """Conv2D + Norm + Activation block (parity: python/paddle/vision/
    ops.py ConvNormActivation — torchvision-style building block)."""

    _DEFAULT = object()  # upstream defaults are BatchNorm2D/ReLU; an
    # EXPLICIT None must disable the layer (torchvision semantics)

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=_DEFAULT,
                 activation_layer=_DEFAULT, dilation=1, bias=None):
        super().__init__()
        from .. import nn as _nn
        if norm_layer is ConvNormActivation._DEFAULT:
            norm_layer = _nn.BatchNorm2D
        if activation_layer is ConvNormActivation._DEFAULT:
            activation_layer = _nn.ReLU
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [_nn.Conv2D(in_channels, out_channels, kernel_size,
                             stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        self._block = _nn.Sequential(*layers)

    def forward(self, x):
        return self._block(x)
