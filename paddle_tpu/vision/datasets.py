"""paddle.vision.datasets (parity: python/paddle/vision/datasets/).

Offline sandbox: downloads are impossible, so dataset classes load from a
local `data_file` when given one and otherwise raise with instructions;
`FakeData` provides a synthetic ImageNet-shaped dataset for benchmarks
(this is what bench.py/config #1 uses until real data is mounted).
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Synthetic classification dataset (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, dtype="float32"):
        # num_classes defaults to 10 (torchvision FakeData parity): the
        # old default of 1000 silently fed out-of-range labels to
        # 10-class models (r5 find)
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx % 65536)
        img = rng.rand(*self.image_shape).astype(self.dtype)
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise RuntimeError(
                "MNIST files not found; this sandbox has no network. Pass "
                "image_path/label_path to local idx files, or use "
                "paddle.vision.datasets.FakeData for synthetic data.")
        self.images = self._load_images(image_path)
        self.labels = self._load_labels(label_path)

    @staticmethod
    def _load_images(path):
        import gzip
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        n = int.from_bytes(data[4:8], "big")
        return np.frombuffer(data, np.uint8, offset=16).reshape(n, 28, 28)

    @staticmethod
    def _load_labels(path):
        import gzip
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        return np.frombuffer(data, np.uint8, offset=8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]



class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "CIFAR archive not found; no network in this sandbox. Pass "
                "data_file=<local cifar-10-python.tar.gz> or use FakeData.")
        self.data, self.labels = self._load(data_file, mode)

    @staticmethod
    def _load(path, mode):
        imgs, labels = [], []
        with tarfile.open(path) as tf:
            names = [n for n in tf.getnames()
                     if ("data_batch" in n if mode == "train" else "test_batch" in n)]
            for n in sorted(names):
                d = pickle.load(tf.extractfile(n), encoding="bytes")
                imgs.append(d[b"data"])
                labels.extend(d.get(b"labels", d.get(b"fine_labels", [])))
        data = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """Image-folder dataset (parity: paddle.vision.datasets.DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.int64(target)


ImageFolder = DatasetFolder


class FashionMNIST(MNIST):
    """Parity: paddle.vision.datasets.FashionMNIST — same idx file
    format as MNIST (offline convention: pass local file paths)."""


class Flowers(Dataset):
    """Parity: paddle.vision.datasets.Flowers (Oxford 102). Offline
    convention: pass local copies of the official files —
    data_file=102flowers.tgz (or the extracted directory CONTAINING
    jpg/), label_file=imagelabels.mat, setid_file=setid.mat. Labels are
    the raw 1-based Oxford classes, as in the reference."""

    _SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        if mode not in self._SPLIT_KEY:
            raise ValueError(
                f"mode must be one of {sorted(self._SPLIT_KEY)}, "
                f"got {mode!r}")
        for f, what in ((data_file, "data_file (102flowers.tgz)"),
                        (label_file, "label_file (imagelabels.mat)"),
                        (setid_file, "setid_file (setid.mat)")):
            if f is None or not os.path.exists(str(f)):
                raise RuntimeError(
                    f"Flowers {what} not found; this sandbox has no "
                    "network — point it at a local copy (or use "
                    "DatasetFolder / FakeData)")
        import scipy.io as sio
        labels = sio.loadmat(str(label_file))["labels"].reshape(-1)
        setid = sio.loadmat(str(setid_file))
        self._indexes = setid[self._SPLIT_KEY[mode]].reshape(-1) \
            .astype(int)  # 1-based image ids
        self._labels = labels
        self._transform = transform
        data_file = str(data_file)
        self._dir = data_file if os.path.isdir(data_file) else None
        self._blobs = None
        if self._dir is None:
            # load this split's members once: random extractfile() on a
            # gzip tar re-decompresses from the archive start on every
            # backward seek, and an open TarFile is unpicklable for
            # DataLoader workers
            wanted = {f"jpg/image_{int(i):05d}.jpg"
                      for i in self._indexes}
            self._blobs = {}
            with tarfile.open(data_file) as tf:
                for m in tf:
                    if m.name in wanted:
                        self._blobs[m.name] = tf.extractfile(m).read()

    def _img_bytes(self, idx1):
        name = f"jpg/image_{idx1:05d}.jpg"
        if self._dir is not None:
            with open(os.path.join(self._dir, name), "rb") as f:
                return f.read()
        return self._blobs[name]

    def __getitem__(self, i):
        import io
        from PIL import Image
        idx1 = int(self._indexes[i])
        img = Image.open(io.BytesIO(self._img_bytes(idx1))).convert("RGB")
        label = int(self._labels[idx1 - 1])  # raw 1-based (reference)
        if self._transform is not None:
            img = self._transform(img)
        return img, np.array([label])

    def __len__(self):
        return len(self._indexes)


class VOC2012(Dataset):
    """Parity: paddle.vision.datasets.VOC2012 — segmentation pairs
    (image, label mask). Offline convention: data_file points at the
    official VOCtrainval tar (or an extracted VOCdevkit directory)."""

    _SPLIT = {"train": "train.txt", "valid": "val.txt",
              "trainval": "trainval.txt"}
    _ROOT = "VOCdevkit/VOC2012"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if mode not in self._SPLIT:
            raise ValueError(
                f"mode must be one of {sorted(self._SPLIT)}, got {mode!r}")
        if data_file is None or not os.path.exists(str(data_file)):
            raise RuntimeError(
                "VOC2012 archive not found; this sandbox has no network. "
                "Point data_file at a local VOCtrainval tar (or the "
                "extracted VOCdevkit), or use DatasetFolder / FakeData.")
        data_file = str(data_file)
        self._dir = data_file if os.path.isdir(data_file) else None
        self._blobs = None
        split = self._SPLIT[mode]
        if self._dir is None:
            # sequential passes: random tar access is pathological on
            # gzip and an open TarFile breaks DataLoader pickling. Pass 1
            # grabs the split list + masks; pass 2 keeps ONLY this
            # split's JPEGs (the full VOC tar holds ~17k images but a
            # segmentation split references <3k — loading all of them
            # would multiply across DataLoader workers)
            self._blobs = {}
            with tarfile.open(data_file) as tf:
                for m in tf:
                    if m.isfile() and (
                            "/SegmentationClass/" in m.name
                            or "/ImageSets/Segmentation/" in m.name):
                        self._blobs[m.name] = tf.extractfile(m).read()
            split_key = f"{self._ROOT}/ImageSets/Segmentation/{split}"
            if split_key not in self._blobs:
                raise RuntimeError(
                    f"VOC2012 archive has no {split_key} — is this the "
                    "official VOCtrainval tar?")
            self._names = [
                n.strip() for n in
                self._blobs[split_key].decode().split("\n") if n.strip()]
            wanted = {f"{self._ROOT}/JPEGImages/{n}.jpg"
                      for n in self._names}
            with tarfile.open(data_file) as tf:
                for m in tf:
                    if m.name in wanted:
                        self._blobs[m.name] = tf.extractfile(m).read()
        else:
            names = self._read(
                f"{self._ROOT}/ImageSets/Segmentation/{split}")
            self._names = [n.strip() for n in names.decode().split("\n")
                           if n.strip()]
        self._transform = transform

    def _read(self, rel):
        if self._dir is not None:
            with open(os.path.join(self._dir, rel), "rb") as f:
                return f.read()
        return self._blobs[rel]

    def __getitem__(self, i):
        import io
        from PIL import Image
        n = self._names[i].strip()
        img = Image.open(io.BytesIO(self._read(
            f"{self._ROOT}/JPEGImages/{n}.jpg"))).convert("RGB")
        mask = Image.open(io.BytesIO(self._read(
            f"{self._ROOT}/SegmentationClass/{n}.png")))
        if self._transform is not None:
            img = self._transform(img)
        return img, mask

    def __len__(self):
        return len(self._names)
