"""paddle.vision.datasets (parity: python/paddle/vision/datasets/).

Offline sandbox: downloads are impossible, so dataset classes load from a
local `data_file` when given one and otherwise raise with instructions;
`FakeData` provides a synthetic ImageNet-shaped dataset for benchmarks
(this is what bench.py/config #1 uses until real data is mounted).
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Synthetic classification dataset (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, dtype="float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx % 65536)
        img = rng.rand(*self.image_shape).astype(self.dtype)
        label = np.int64(idx % self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise RuntimeError(
                "MNIST files not found; this sandbox has no network. Pass "
                "image_path/label_path to local idx files, or use "
                "paddle.vision.datasets.FakeData for synthetic data.")
        self.images = self._load_images(image_path)
        self.labels = self._load_labels(label_path)

    @staticmethod
    def _load_images(path):
        import gzip
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        n = int.from_bytes(data[4:8], "big")
        return np.frombuffer(data, np.uint8, offset=16).reshape(n, 28, 28)

    @staticmethod
    def _load_labels(path):
        import gzip
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        return np.frombuffer(data, np.uint8, offset=8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


FashionMNIST = MNIST


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "CIFAR archive not found; no network in this sandbox. Pass "
                "data_file=<local cifar-10-python.tar.gz> or use FakeData.")
        self.data, self.labels = self._load(data_file, mode)

    @staticmethod
    def _load(path, mode):
        imgs, labels = [], []
        with tarfile.open(path) as tf:
            names = [n for n in tf.getnames()
                     if ("data_batch" in n if mode == "train" else "test_batch" in n)]
            for n in sorted(names):
                d = pickle.load(tf.extractfile(n), encoding="bytes")
                imgs.append(d[b"data"])
                labels.extend(d.get(b"labels", d.get(b"fine_labels", [])))
        data = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """Image-folder dataset (parity: paddle.vision.datasets.DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.int64(target)


ImageFolder = DatasetFolder


class FashionMNIST(MNIST):
    """Parity: paddle.vision.datasets.FashionMNIST — same idx file
    format as MNIST (offline convention: pass local file paths)."""


class Flowers(Dataset):
    """Parity: paddle.vision.datasets.Flowers. Offline sandbox: load
    from a local directory of class-subfolder images via DatasetFolder,
    or use FakeData."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        if data_file is None or not os.path.exists(str(data_file)):
            raise RuntimeError(
                "Flowers archive not found; this sandbox has no network. "
                "Point data_file at a local copy, use DatasetFolder over "
                "an extracted image tree, or FakeData for synthetic data.")
        raise NotImplementedError(
            "Flowers .mat parsing needs scipy.io over the local archive; "
            "extract the images and use DatasetFolder instead")


class VOC2012(Dataset):
    """Parity: paddle.vision.datasets.VOC2012 (offline convention)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None or not os.path.exists(str(data_file)):
            raise RuntimeError(
                "VOC2012 archive not found; this sandbox has no network. "
                "Point data_file at a local VOCtrainval tar, or use "
                "DatasetFolder / FakeData.")
        raise NotImplementedError(
            "VOC2012 segmentation parsing lands with a local archive; "
            "extract and use DatasetFolder for classification use")
