"""paddle.vision parity namespace."""
from . import models
from . import transforms
from . import datasets
from . import ops
from .models import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, LeNet, VGG, vgg16, MobileNetV2, mobilenet_v2)


_IMAGE_BACKEND = "pil"


def set_image_backend(backend):
    """Parity: paddle.vision.set_image_backend ('pil' or 'cv2')."""
    global _IMAGE_BACKEND
    if backend not in ("pil", "cv2"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _IMAGE_BACKEND = backend


def get_image_backend():
    return _IMAGE_BACKEND


def image_load(path, backend=None):
    """Parity: paddle.vision.image_load — loads an image file with the
    configured backend (PIL here; cv2 is not shipped in this image)."""
    b = backend or _IMAGE_BACKEND
    if b == "cv2":
        raise RuntimeError("cv2 backend not available in this "
                           "environment; use set_image_backend('pil')")
    from PIL import Image
    return Image.open(path)
