"""paddle.vision parity namespace."""
from . import models
from . import transforms
from . import datasets
from . import ops
from .models import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, LeNet, VGG, vgg16, MobileNetV2, mobilenet_v2)


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"
