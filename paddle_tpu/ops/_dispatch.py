"""Eager op dispatch: the (tiny) TPU-native equivalent of Paddle's generated
eager API layer.

Reference parity: in Paddle every `paddle._C_ops.<op>` call goes through a
generated `*_ad_func` (paddle/fluid/eager/api/generated/) that runs the
kernel and wires a GradNode (paddle/fluid/eager/auto_code_generator/).
Here a single generic `apply(fn, *tensor_args)` does both jobs:

- fast path (no grad needed): run the pure-jax `fn` directly;
- tape path: `jax.vjp(fn, *arrays)` computes the primal AND captures the
  pullback, which becomes the GradNode's backward. The pullback is itself
  jax-traceable, so backward with `create_graph=True` routes back through
  `apply`, giving higher-order autograd with no codegen.

There is no kernel registry/InferMeta: XLA abstract evaluation performs
shape/dtype inference, and kernel selection is XLA compilation.
"""
from __future__ import annotations

from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, _is_tracer
from ..autograd.grad_mode import is_grad_enabled
from ..autograd.engine import GradNode

float0 = jax.dtypes.float0


_amp_fn = None


def _amp_dtype_for(name):
    if not name:
        return None
    global _amp_fn
    if _amp_fn is None:
        from ..amp import amp_dtype_for
        _amp_fn = amp_dtype_for
    return _amp_fn(name)


def as_array(x):
    """Coerce an op argument to something jax accepts."""
    if isinstance(x, Tensor):
        return x._value
    return x


def _is_inexact(d) -> bool:
    return jnp.issubdtype(d, jnp.inexact)


def _wrap_outputs(out, node):
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    tensors = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=node is None)
        if node is not None:
            t._grad_node = node
            t._out_index = i
            node.register_output(i, t)
        tensors.append(t)
    return tuple(tensors) if multi else tensors[0]


def _make_backward(fn, arrays, vjp_fn, multi_out, out_shapes, out_dtypes,
                   diff_in_idx, tensor_inputs):
    """GradNode backward: engine passes full cotangent Tensors (one per
    output); we feed only inexact-output cotangents through the pullback
    (int/bool outputs get float0 zeros) and scatter the pullback's results
    back to the input slots.

    With create_graph the saved pullback is NOT enough — its residuals hide
    the dependence on the primal inputs, so d(grad)/d(primal) would be lost.
    Instead we re-derive the pullback inside a fresh traced function of
    (cotangents, primal inputs), recomputing the forward (the standard
    double-backward recompute), so the tape records edges to the primals.
    """
    n_inputs = len(arrays)
    diff_out_idx = [i for i, d in enumerate(out_dtypes) if _is_inexact(d)]
    n_dout = len(diff_out_idx)

    def _rebuild_cots(diff_cots):
        full = []
        k = 0
        for i, d in enumerate(out_dtypes):
            if _is_inexact(d):
                c = diff_cots[k]
                k += 1
                if c.dtype != d:
                    c = c.astype(d)
                full.append(c)
            else:
                full.append(np.zeros(out_shapes[i], float0))
        return tuple(full) if multi_out else full[0]

    def run_saved(*diff_cots):
        grads = vjp_fn(_rebuild_cots(diff_cots))
        return tuple(grads[i] for i in diff_in_idx)

    def run_fresh(*flat):
        diff_cots = flat[:n_dout]
        prim = list(arrays)
        for k, slot in enumerate(diff_in_idx):
            prim[slot] = flat[n_dout + k]
        _, pull = jax.vjp(fn, *prim)
        grads = pull(_rebuild_cots(diff_cots))
        return tuple(grads[i] for i in diff_in_idx)

    def backward_fn(cot_tensors, create_graph):
        diff_cots = [cot_tensors[i] for i in diff_out_idx]
        if create_graph:
            prims = [tensor_inputs[i] for i in diff_in_idx]
            res = apply(run_fresh, *diff_cots, *prims)
        else:
            res = apply(run_saved, *diff_cots)
        if isinstance(res, Tensor):
            res = (res,)
        out = [None] * n_inputs
        for slot, g in zip(diff_in_idx, res):
            out[slot] = g
        return out

    return backward_fn


#: Active static.Program capturing the op stream (set by
#: static.program_guard). Each recorded entry is (fn, input refs, output
#: tensors); Executor.run replays them with substituted feed values —
#: the facade's stand-in for the reference's ProgramDesc op list.
_static_recorder = None


def _record_static(fn, args, result):
    if _static_recorder is not None:
        outs = list(result) if isinstance(result, tuple) else [result]
        _static_recorder._build_ops.append((fn, list(args), outs))
    return result


def apply(fn: Callable, *args, _name: str = ""):
    """Run `fn(*arrays)` with tape recording.

    `fn` must be a pure jax function over the positional array args (close
    static attrs over it). Returns a Tensor, or a tuple of Tensors when fn
    returns a tuple/list.
    """
    arrays = tuple(a._value if isinstance(a, Tensor) else a for a in args)
    _debug_hooks(_name, arrays)
    # amp O1/O2 hook: cast float inputs of white/black-listed ops
    amp_d = _amp_dtype_for(_name)
    if amp_d is not None:
        arrays = tuple(
            a.astype(amp_d) if (hasattr(a, "dtype")
                                and jnp.issubdtype(a.dtype, jnp.floating)
                                and a.dtype != amp_d
                                and a.dtype != jnp.float64)
            else a for a in arrays)
    needs_grad = False
    if is_grad_enabled():
        for a in args:
            if isinstance(a, Tensor) and not a.stop_gradient:
                needs_grad = True
                break
    if needs_grad:
        # only float-like Tensor inputs can carry gradients
        diff_in_idx = [i for i, a in enumerate(args)
                       if isinstance(a, Tensor)
                       and hasattr(arrays[i], "dtype")
                       and _is_inexact(arrays[i].dtype)]
        if not diff_in_idx:
            needs_grad = False
    if not needs_grad:
        return _record_static(fn, args, _wrap_outputs(fn(*arrays), None))

    if any(_is_tracer(a) for a in arrays):
        # Inside an outer jax trace (TrainStep / functionalize / jit.grad):
        # the outer transform differentiates the traced ops directly —
        # including custom_vjp kernels. A nested jax.vjp here would
        # re-linearize every custom_vjp fwd under the outer trace, which
        # Pallas kernels cannot survive (pallas_call has no JVP rule:
        # "Linearization failed to produce known values"). Record nothing;
        # the eager tape is only meaningful on concrete values.
        return _wrap_outputs(fn(*arrays), None)  # tracer: no static record

    out, vjp_fn = jax.vjp(fn, *arrays)
    multi_out = isinstance(out, (tuple, list))
    outs_list = list(out) if multi_out else [out]
    out_shapes = [tuple(o.shape) for o in outs_list]
    out_dtypes = [o.dtype for o in outs_list]
    if not any(_is_inexact(d) for d in out_dtypes):
        # all-integer outputs (argmax etc.) — nothing to differentiate
        return _record_static(fn, args, _wrap_outputs(out, None))
    tensor_inputs = [a if isinstance(a, Tensor) else None for a in args]
    node = GradNode(
        _make_backward(fn, arrays, vjp_fn, multi_out, out_shapes, out_dtypes,
                       diff_in_idx, tensor_inputs),
        tensor_inputs, outs_list,
        name=_name or getattr(fn, "__name__", "op"))
    return _record_static(fn, args, _wrap_outputs(out, node))


# ---------------------------------------------------------------------------
# Debug hooks: FLAGS_check_nan_inf (reference parity:
# paddle/fluid/framework/details/nan_inf_utils_detail — every kernel's
# outputs scanned when the flag is on) and the amp operator-stats
# collector (paddle.amp.debugging.collect_operator_stats).
# ---------------------------------------------------------------------------

_op_stats = None  # dict[(op, dtype)] -> count when collection is on


def _debug_hooks(name, arrays):
    global _op_stats
    if _op_stats is not None:
        key_dtype = ""
        for a in arrays:
            if hasattr(a, "dtype"):
                key_dtype = str(a.dtype)
                break
        k = (name or "<anon>", key_dtype)
        _op_stats[k] = _op_stats.get(k, 0) + 1
    from ..framework.flags import flag_value
    if flag_value("check_nan_inf"):
        for i, a in enumerate(arrays):
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact):
                if _is_tracer(a):
                    # under jit/grad a concrete count is unavailable; the
                    # flag's on_change already enabled jax_debug_nans,
                    # which traps non-finite values in compiled programs
                    # at runtime — skip the eager scan here
                    continue
                bad = int(jnp.sum(~jnp.isfinite(a)))
                if bad:
                    raise FloatingPointError(
                        f"FLAGS_check_nan_inf: op '{name or '<anon>'}' "
                        f"input #{i} contains {bad} non-finite values")
