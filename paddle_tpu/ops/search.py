"""Search/sort ops (parity: python/paddle/tensor/search.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework import dtype as dtypes
from ._dispatch import apply
from .creation import _coerce


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    def fn(v):
        if axis is None:
            out = jnp.argmax(v.reshape(-1))
            return out.astype(d)
        out = jnp.argmax(v, axis=int(axis), keepdims=keepdim)
        return out.astype(d)
    return apply(fn, _coerce(x))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    def fn(v):
        if axis is None:
            return jnp.argmin(v.reshape(-1)).astype(d)
        return jnp.argmin(v, axis=int(axis), keepdims=keepdim).astype(d)
    return apply(fn, _coerce(x))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        idx = jnp.argsort(v, axis=axis, stable=stable or True,
                          descending=descending)
        return idx.astype(dtypes.int64)
    return apply(fn, _coerce(x))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis, stable=stable or True,
                       descending=descending)
        return out
    return apply(fn, _coerce(x))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)
    def fn(v):
        ax = v.ndim - 1 if axis is None else int(axis) % v.ndim
        vv = jnp.moveaxis(v, ax, -1) if ax != v.ndim - 1 else v
        if largest:
            vals, idx = jax.lax.top_k(vv, kk)
        else:
            vals, idx = jax.lax.top_k(-vv, kk)
            vals = -vals
        if ax != v.ndim - 1:
            vals = jnp.moveaxis(vals, -1, ax)
            idx = jnp.moveaxis(idx, -1, ax)
        return vals, idx.astype(dtypes.int64)
    return apply(fn, _coerce(x))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = int(axis) % v.ndim
        srt = jnp.sort(v, axis=ax)
        arg = jnp.argsort(v, axis=ax).astype(dtypes.int64)
        vals = jnp.take(srt, k - 1, axis=ax)
        idx = jnp.take(arg, k - 1, axis=ax)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        return vals, idx
    return apply(fn, _coerce(x))


def mode(x, axis=-1, keepdim=False, name=None):
    x = _coerce(x)
    def fn(v):
        ax = int(axis) % v.ndim
        srt = jnp.sort(v, axis=ax)
        n = v.shape[ax]
        # run lengths in sorted order (mode = value with max run length):
        # start-of-run flags -> running max of start positions gives each
        # element its run start; length = pos - start + 1. (The previous
        # `associative_scan(b*(a+1))` combine was NOT associative and
        # produced wrong run lengths for some inputs — r4 fuzz find.)
        is_start = jnp.concatenate(
            [jnp.ones_like(jnp.take(srt, jnp.arange(1), axis=ax),
                           dtype=bool),
             jnp.take(srt, jnp.arange(1, n), axis=ax) !=
             jnp.take(srt, jnp.arange(n - 1), axis=ax)], axis=ax)
        shape = [1] * v.ndim
        shape[ax] = n
        pos = jnp.arange(n, dtype=jnp.int32).reshape(shape)
        start = jax.lax.cummax(jnp.where(is_start, pos, 0), axis=ax)
        run = pos - start + 1
        # argmax picks the FIRST maximal run -> smallest modal value on
        # ties (matching torch/paddle tie behavior on sorted data)
        best = jnp.argmax(run, axis=ax, keepdims=True)
        vals = jnp.take_along_axis(srt, best, axis=ax)
        # paddle returns the index of (one) occurrence in the original array
        match = v == vals
        idx = jnp.argmax(match, axis=ax, keepdims=True).astype(dtypes.int64)
        if not keepdim:
            vals = jnp.squeeze(vals, axis=ax)
            idx = jnp.squeeze(idx, axis=ax)
        return vals, idx
    return apply(fn, x)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    """Parity: paddle.searchsorted — an N-D sorted_sequence searches
    row-wise (innermost dim), with leading dims matching `values`
    (jnp.searchsorted is 1-D only; rows vmap — r5 fuzz find)."""
    side = "right" if right else "left"
    d = dtypes.int32 if out_int32 else dtypes.int64

    def fn(s, v):
        if s.ndim <= 1:
            return jnp.searchsorted(s, v, side=side).astype(d)
        if s.shape[:-1] != v.shape[:-1]:
            raise ValueError(
                f"searchsorted: leading dims of sorted_sequence "
                f"{s.shape} must match values {v.shape}")
        flat_s = s.reshape(-1, s.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        out = jax.vmap(lambda ss, vv: jnp.searchsorted(
            ss, vv, side=side))(flat_s, flat_v)
        return out.reshape(v.shape).astype(d)

    return apply(fn, _coerce(sorted_sequence), _coerce(values))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic output shape → host-side (parity: paddle op is dynamic too)
    arr = np.asarray(_coerce(x)._value)
    res = np.unique(arr, return_index=True, return_inverse=True,
                    return_counts=True, axis=axis)
    vals, idx, inv, cnt = res
    d = dtypes.convert_dtype(dtype)
    outs = [Tensor(jnp.asarray(vals))]
    if return_index:
        outs.append(Tensor(jnp.asarray(idx, dtype=d)))
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inv.reshape(arr.shape if axis is None else -1), dtype=d)))
    if return_counts:
        outs.append(Tensor(jnp.asarray(cnt, dtype=d)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(_coerce(x)._value)
    if axis is None:
        arr = arr.reshape(-1)
        ax = 0
    else:
        ax = axis
    sel = np.ones(arr.shape[ax], dtype=bool)
    if arr.shape[ax] > 1:
        a = np.take(arr, range(1, arr.shape[ax]), axis=ax)
        b = np.take(arr, range(arr.shape[ax] - 1), axis=ax)
        neq = (a != b)
        while neq.ndim > 1:
            neq = neq.any(axis=-1 if ax == 0 else 0)
        sel[1:] = neq
    vals = np.compress(sel, arr, axis=ax)
    d = dtypes.convert_dtype(dtype)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(sel) - 1
        outs.append(Tensor(jnp.asarray(inv, dtype=d)))
    if return_counts:
        pos = np.flatnonzero(sel)
        cnt = np.diff(np.append(pos, arr.shape[ax]))
        outs.append(Tensor(jnp.asarray(cnt, dtype=d)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def nanargmax(x, axis=None, keepdim=False, name=None):
    """argmax ignoring NaNs (parity: python/paddle/tensor/search.py)."""
    def fn(v):
        if axis is None:
            return jnp.nanargmax(v.reshape(-1)).astype(dtypes.int64)
        return jnp.nanargmax(v, axis=int(axis), keepdims=keepdim
                             ).astype(dtypes.int64)
    return apply(fn, _coerce(x))


def nanargmin(x, axis=None, keepdim=False, name=None):
    """argmin ignoring NaNs (parity: python/paddle/tensor/search.py)."""
    def fn(v):
        if axis is None:
            return jnp.nanargmin(v.reshape(-1)).astype(dtypes.int64)
        return jnp.nanargmin(v, axis=int(axis), keepdims=keepdim
                             ).astype(dtypes.int64)
    return apply(fn, _coerce(x))


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling (parity: python/paddle/tensor/search.py
    top_p_sampling; upstream phi top_p_sampling CUDA kernel). x: [B, V]
    probabilities; ps: [B] cumulative-probability cutoffs. Returns
    (sampled probs [B, 1], token ids [B, 1])."""
    from ..framework.random import next_key
    # paddle sentinel: seed=-1 (the default) means non-deterministic
    if seed is None or int(seed) < 0:
        key = next_key()
    else:
        key = jax.random.PRNGKey(int(seed))
    args = [_coerce(x), _coerce(ps)]
    if threshold is not None:
        args.append(_coerce(threshold))

    def fn(v, p, *rest):
        order = jnp.argsort(-v, axis=-1)
        sorted_p = jnp.take_along_axis(v, order, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        # keep tokens while cumulative mass (exclusive) < p
        keep = (cum - sorted_p) < p[:, None]
        keep = keep.at[:, 0].set(True)  # always keep the top token
        if rest:  # probability floor (paddle threshold semantics)
            keep = jnp.logical_and(keep,
                                   sorted_p >= rest[0].reshape(-1, 1))
            keep = keep.at[:, 0].set(True)
        masked = jnp.where(keep, sorted_p, 0.0)
        masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
        pick = jax.random.categorical(key, jnp.log(masked + 1e-30),
                                      axis=-1)                 # [B]
        ids = jnp.take_along_axis(order, pick[:, None], axis=-1)
        probs = jnp.take_along_axis(v, ids, axis=-1)
        return probs, ids.astype(jnp.int64)
    return apply(fn, *args)
