"""Additional tensor ops (parity: the long tail of python/paddle/tensor/*
— stacking/splitting, scatter variants, special functions, NCHW shuffles).

Same design as ops/math.py: thin Paddle-signature wrappers over jax.numpy
through the tape dispatch; XLA fuses and tiles them.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ._dispatch import apply
from .creation import _coerce

__all__ = [
    "hstack", "vstack", "dstack", "column_stack", "row_stack",
    "tensor_split", "hsplit", "vsplit", "dsplit", "unflatten",
    "isin", "vander", "trapezoid", "cumulative_trapezoid",
    "sinc", "signbit", "isposinf", "isneginf", "isreal",
    "polygamma", "gammaln", "gammainc", "gammaincc", "multigammaln",
    "frexp", "ldexp", "logaddexp2", "xlogy", "float_power",
    "index_fill", "masked_scatter", "select_scatter", "slice_scatter",
    "renorm", "block_diag", "pdist", "positive", "negative",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "cartesian_prod", "combinations", "histogram_bin_edges",
]


def _t(x):
    return _coerce(x)


# ------------------------------------------------------------- stacking ---

def hstack(x, name=None):
    return apply(lambda *vs: jnp.hstack(vs), *[_t(v) for v in x],
                 _name="hstack")


def vstack(x, name=None):
    return apply(lambda *vs: jnp.vstack(vs), *[_t(v) for v in x],
                 _name="vstack")


def dstack(x, name=None):
    return apply(lambda *vs: jnp.dstack(vs), *[_t(v) for v in x],
                 _name="dstack")


def column_stack(x, name=None):
    return apply(lambda *vs: jnp.column_stack(vs), *[_t(v) for v in x],
                 _name="column_stack")


row_stack = vstack


def tensor_split(x, num_or_indices, axis=0, name=None):
    t = _t(x)
    n = num_or_indices
    if isinstance(n, int):
        parts = np.array_split(np.arange(t.shape[axis]), n)
        sizes = [len(p) for p in parts]
        offs = np.cumsum([0] + sizes)[:-1]
    else:
        idx = [int(i) for i in n]
        offs = [0] + idx
        sizes = [b - a for a, b in
                 zip(offs, idx + [t.shape[axis]])]
    outs = []
    for off, size in zip(offs, sizes):
        outs.append(apply(
            lambda v, off=off, size=size: jax.lax.slice_in_dim(
                v, off, off + size, axis=axis), t, _name="tensor_split"))
    return outs


def hsplit(x, num_or_indices, name=None):
    t = _t(x)
    ax = 0 if t.ndim == 1 else 1
    return tensor_split(t, num_or_indices, axis=ax)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def unflatten(x, axis, shape, name=None):
    t = _t(x)
    shape = [int(s) for s in (shape.tolist() if isinstance(shape, Tensor)
                              else shape)]
    ax = axis % t.ndim
    full = list(t.shape[:ax]) + shape + list(t.shape[ax + 1:])
    if -1 in shape:
        pass  # jnp.reshape resolves the -1
    return apply(lambda v: v.reshape(full), t, _name="unflatten")


# -------------------------------------------------------------- queries ---

def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply(lambda a, b: jnp.isin(a, b, invert=invert),
                 _t(x), _t(test_x), _name="isin")


def signbit(x, name=None):
    return apply(jnp.signbit, _t(x), _name="signbit")


def isposinf(x, name=None):
    return apply(jnp.isposinf, _t(x), _name="isposinf")


def isneginf(x, name=None):
    return apply(jnp.isneginf, _t(x), _name="isneginf")


def isreal(x, name=None):
    return apply(jnp.isreal, _t(x), _name="isreal")


# ---------------------------------------------------------------- math ----

def vander(x, n=None, increasing=False, name=None):
    return apply(lambda v: jnp.vander(v, N=n, increasing=increasing),
                 _t(x), _name="vander")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    args = [_t(y)]
    if x is not None:
        args.append(_t(x))

        def fn(yv, xv):
            return jax.scipy.integrate.trapezoid(yv, xv, axis=axis)
    else:
        d = 1.0 if dx is None else float(dx)

        def fn(yv):
            return jax.scipy.integrate.trapezoid(yv, dx=d, axis=axis)
    return apply(fn, *args, _name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    d = 1.0 if dx is None else float(dx)

    def _cumtrap(yv, xv=None):
        y1 = jnp.moveaxis(yv, axis, -1)
        if xv is not None:
            x1 = jnp.moveaxis(jnp.broadcast_to(xv, yv.shape), axis, -1)
            widths = jnp.diff(x1, axis=-1)
        else:
            widths = d
        avg = (y1[..., 1:] + y1[..., :-1]) / 2.0
        out = jnp.cumsum(avg * widths, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    if x is not None:
        return apply(lambda yv, xv: _cumtrap(yv, xv), _t(y), _t(x),
                     _name="cumulative_trapezoid")
    return apply(_cumtrap, _t(y), _name="cumulative_trapezoid")


def sinc(x, name=None):
    return apply(jnp.sinc, _t(x), _name="sinc")


def polygamma(x, n, name=None):
    return apply(lambda v: jax.scipy.special.polygamma(int(n), v), _t(x),
                 _name="polygamma")


def gammaln(x, name=None):
    return apply(jax.scipy.special.gammaln, _t(x), _name="gammaln")


def gammainc(x, y, name=None):
    return apply(jax.scipy.special.gammainc, _t(x), _t(y),
                 _name="gammainc")


def gammaincc(x, y, name=None):
    return apply(jax.scipy.special.gammaincc, _t(x), _t(y),
                 _name="gammaincc")


def multigammaln(x, p, name=None):
    return apply(lambda v: jax.scipy.special.multigammaln(v, int(p)),
                 _t(x), _name="multigammaln")


def frexp(x, name=None):
    return apply(lambda v: jnp.frexp(v), _t(x), _name="frexp")


def ldexp(x, y, name=None):
    return apply(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)),
                 _t(x), _t(y), _name="ldexp")


def logaddexp2(x, y, name=None):
    return apply(jnp.logaddexp2, _t(x), _t(y), _name="logaddexp2")


def xlogy(x, y, name=None):
    return apply(jax.scipy.special.xlogy, _t(x), _t(y), _name="xlogy")


def float_power(x, y, name=None):
    return apply(lambda a, b: jnp.power(a.astype(jnp.float64)
                                        if jax.config.jax_enable_x64
                                        else a.astype(jnp.float32),
                                        b), _t(x), _t(y),
                 _name="float_power")


def positive(x, name=None):
    return apply(lambda v: +v, _t(x), _name="positive")


def negative(x, name=None):
    return apply(jnp.negative, _t(x), _name="negative")


# ------------------------------------------------------------- scatters ---

def index_fill(x, index, axis, value, name=None):
    def fn(v, idx):
        moved = jnp.moveaxis(v, axis, 0)
        filled = moved.at[idx].set(jnp.asarray(value, v.dtype))
        return jnp.moveaxis(filled, 0, axis)
    return apply(fn, _t(x), _t(index), _name="index_fill")


def masked_scatter(x, mask, value, name=None):
    def fn(v, m, src):
        mb = jnp.broadcast_to(m, v.shape)
        # k-th True position takes src.flatten()[k] (paddle/torch order)
        order = jnp.cumsum(mb.reshape(-1).astype(jnp.int32)) - 1
        picked = src.reshape(-1)[jnp.clip(order, 0, src.size - 1)]
        return jnp.where(mb, picked.reshape(v.shape), v)
    return apply(fn, _t(x), _t(mask), _t(value), _name="masked_scatter")


def select_scatter(x, values, axis, index, name=None):
    def fn(v, src):
        moved = jnp.moveaxis(v, axis, 0)
        out = moved.at[index].set(src.astype(v.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply(fn, _t(x), _t(values), _name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(v, src):
        idx = [slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[int(ax)] = slice(int(st), int(en), int(sd))
        return v.at[tuple(idx)].set(src.astype(v.dtype))
    return apply(fn, _t(x), _t(value), _name="slice_scatter")


# --------------------------------------------------------------- linalg ---

def renorm(x, p, axis, max_norm, name=None):
    def fn(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply(fn, _t(x), _name="renorm")


def block_diag(inputs, name=None):
    ts = [_t(v) for v in inputs]

    def fn(*vs):
        vs = [v.reshape(1, 1) if v.ndim == 0
              else (v.reshape(1, -1) if v.ndim == 1 else v) for v in vs]
        return jax.scipy.linalg.block_diag(*vs)
    return apply(fn, *ts, _name="block_diag")


def pdist(x, p=2.0, name=None):
    def fn(v):
        n = v.shape[0]
        d = jnp.linalg.norm(v[:, None, :] - v[None, :, :], ord=p, axis=-1)
        iu = jnp.triu_indices(n, k=1)
        return d[iu]
    return apply(fn, _t(x), _name="pdist")


# ----------------------------------------------------- vision reshuffles --

def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            oc = c // (r * r)
            v = v.reshape(b, oc, r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(b, oc, h * r, w * r)
        b, h, w, c = v.shape
        oc = c // (r * r)
        v = v.reshape(b, h, w, r, r, oc)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(b, h * r, w * r, oc)
    return apply(fn, _t(x), _name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            oh, ow = h // r, w // r
            v = v.reshape(b, c, oh, r, ow, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(b, c * r * r, oh, ow)
        b, h, w, c = v.shape
        oh, ow = h // r, w // r
        v = v.reshape(b, oh, r, ow, r, c)
        v = v.transpose(0, 2, 4, 1, 3, 5)
        return v.reshape(b, oh, ow, c * r * r)
    return apply(fn, _t(x), _name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, g, c // g, h, w)
            return v.transpose(0, 2, 1, 3, 4).reshape(b, c, h, w)
        b, h, w, c = v.shape
        v = v.reshape(b, h, w, g, c // g)
        return v.transpose(0, 1, 2, 4, 3).reshape(b, h, w, c)
    return apply(fn, _t(x), _name="channel_shuffle")


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (parity: python/paddle/tensor/
    math.py cartesian_prod)."""
    xs = [_coerce(t) for t in (x if isinstance(x, (list, tuple)) else [x])]

    def fn(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    out = apply(fn, *xs)
    return out


def combinations(x, r=2, with_replacement=False, name=None):
    """r-combinations of a 1-D tensor (parity: python/paddle/tensor/
    math.py combinations)."""
    import itertools as _it
    n = _coerce(x).shape[0]
    gen = (_it.combinations_with_replacement if with_replacement
           else _it.combinations)
    idx = np.asarray(list(gen(range(n), r)), np.int32).reshape(-1, r)

    def fn(v):
        return v[idx]
    return apply(fn, _coerce(x))


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    """Parity: python/paddle/tensor/linalg.py histogram_bin_edges."""
    def fn(v):
        lo, hi = ((min, max) if (min != 0 or max != 0)
                  else (v.min(), v.max()))
        return jnp.histogram_bin_edges(v, bins=bins, range=(lo, hi))
    return apply(fn, _coerce(x))
