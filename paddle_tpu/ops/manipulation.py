"""Shape/layout manipulation ops (parity: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ._dispatch import apply, as_array
from ..framework import dtype as dtypes
from .creation import _coerce


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    out = []
    for s in shape:
        out.append(int(s._value) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape(x, shape, name=None):
    sh = _static_shape(shape)
    return apply(lambda v: jnp.reshape(v, sh), _coerce(x))


def reshape_(x, shape, name=None):
    x._check_inplace()
    return x._inplace_update(reshape(x, shape))


def transpose(x, perm=None, name=None):
    x = _coerce(x)
    if perm is None:
        perm = list(reversed(range(x.ndim)))
    perm = [int(p) for p in perm]
    return apply(lambda v: jnp.transpose(v, perm), x)


def t(x, name=None):
    x = _coerce(x)
    if x.ndim < 2:
        return apply(lambda v: v, x)
    return transpose(x, [1, 0])


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), _coerce(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda v: jnp.swapaxes(v, axis0, axis1), _coerce(x))


transpose_ = swapaxes  # not paddle but harmless internal alias


def concat(x, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    ts = [_coerce(t) for t in x]
    return apply(lambda *vs: jnp.concatenate(vs, axis=ax), *ts)


def stack(x, axis=0, name=None):
    ts = [_coerce(t) for t in x]
    return apply(lambda *vs: jnp.stack(vs, axis=int(axis)), *ts)


def split(x, num_or_sections, axis=0, name=None):
    x = _coerce(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x._value.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections:
            # paddle contract: an int num must evenly divide the axis
            # (the old silent floor-split put the remainder in the last
            # chunk — r5 fuzz find); pass explicit sections for ragged
            raise ValueError(
                f"paddle.split: axis {ax} (size {dim}) is not divisible "
                f"by num_or_sections={num_or_sections}; pass a sections "
                "list for uneven splits")
        idx = np.cumsum([dim // num_or_sections] * (num_or_sections - 1))
    else:
        secs = [int(s) for s in num_or_sections]
        # paddle allows one -1 section
        if -1 in secs:
            known = builtins_sum(s for s in secs if s != -1)
            secs[secs.index(-1)] = dim - known
        idx = np.cumsum(secs[:-1])
    return apply(lambda v: tuple(jnp.split(v, idx, axis=ax)), x)


def builtins_sum(it):
    tot = 0
    for v in it:
        tot += v
    return tot


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = _coerce(x)
    n = x._value.shape[axis]
    def fn(v):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis))
    return apply(fn, x)


unstack = unbind


def squeeze(x, axis=None, name=None):
    x = _coerce(x)
    if axis is None:
        ax = None
    else:
        axs = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(int(a) for a in axs if x._value.shape[int(a)] == 1)
    return apply(lambda v: jnp.squeeze(v, axis=ax), x)


def squeeze_(x, axis=None, name=None):
    x._check_inplace()
    return x._inplace_update(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    axs = axis if isinstance(axis, (list, tuple)) else [axis]
    axs = tuple(int(a.item()) if isinstance(a, Tensor) else int(a) for a in axs)
    return apply(lambda v: jnp.expand_dims(v, axs), _coerce(x))


def unsqueeze_(x, axis, name=None):
    x._check_inplace()
    return x._inplace_update(unsqueeze(x, axis))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _coerce(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    def fn(v):
        sh = v.shape
        mid = 1
        for d in sh[s:e + 1]:
            mid *= d
        return jnp.reshape(v, sh[:s] + (mid,) + sh[e + 1:])
    return apply(fn, x)


def expand(x, shape, name=None):
    sh = _static_shape(shape)
    x = _coerce(x)
    def fn(v):
        tgt = list(sh)
        # paddle: -1 keeps the original dim
        off = len(tgt) - v.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tuple(tgt))
    return apply(fn, x)


broadcast_to = expand


def expand_as(x, y, name=None):
    y = _coerce(y)
    return expand(x, list(y._value.shape))


def broadcast_tensors(inputs, name=None):
    ts = [_coerce(t) for t in inputs]
    return apply(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *ts)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply(lambda v: jnp.tile(v, reps), _coerce(x))


def flip(x, axis, name=None):
    axs = axis if isinstance(axis, (list, tuple)) else [axis]
    axs = tuple(int(a) for a in axs)
    return apply(lambda v: jnp.flip(v, axis=axs), _coerce(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), _coerce(x))


def roll(x, shifts, axis=None, name=None):
    sh = shifts if not isinstance(shifts, Tensor) else tuple(shifts.tolist())
    return apply(lambda v: jnp.roll(v, sh, axis=axis), _coerce(x))


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i,
                                       axis=ax), _coerce(x), _coerce(index))


def gather_nd(x, index, name=None):
    def fn(v, idx):
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return apply(fn, _coerce(x), _coerce(index))


def take(x, index, mode="raise", name=None):
    md = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply(lambda v, i: jnp.take(v.reshape(-1), i, mode=md),
                 _coerce(x), _coerce(index))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                 _coerce(arr), _coerce(indices))


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    from .math import _scalarize
    def fn(v, i, val):
        val = jnp.broadcast_to(jnp.asarray(val, v.dtype), i.shape)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, val, axis=axis, inplace=False)
        idx_full = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(i.ndim)])
                    for k, s in enumerate(i.shape)]
        idx_full[axis] = i
        at = v.at[tuple(idx_full)]
        if reduce == "add":
            return at.add(val)
        if reduce in ("mul", "multiply"):
            return at.multiply(val)
        if reduce == "amax":
            return at.max(val)
        if reduce == "amin":
            return at.min(val)
        raise ValueError(f"unknown reduce {reduce}")
    return apply(fn, _coerce(arr), _coerce(indices), _scalarize(values))


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        if overwrite:
            return v.at[i].set(u)
        # paddle overwrite=False: zero target rows then accumulate
        z = v.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)
    return apply(fn, _coerce(x), _coerce(index), _coerce(updates))


def scatter_(x, index, updates, overwrite=True, name=None):
    x._check_inplace()
    return x._inplace_update(scatter(x, index, updates, overwrite))


def scatter_nd(index, updates, shape, name=None):
    sh = _static_shape(shape)
    def fn(i, u):
        out = jnp.zeros(sh, u.dtype)
        return out.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(fn, _coerce(index), _coerce(updates))


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(fn, _coerce(x), _coerce(index), _coerce(updates))


def index_select(x, index, axis=0, name=None):
    return apply(lambda v, i: jnp.take(v, i, axis=axis),
                 _coerce(x), _coerce(index))


def index_sample(x, index, name=None):
    def fn(v, i):
        rows = jnp.arange(v.shape[0])[:, None]
        return v[rows, i]
    return apply(fn, _coerce(x), _coerce(index))


def index_add(x, index, axis, value, name=None):
    def fn(v, i, val):
        vm = jnp.moveaxis(v, axis, 0)
        vm = vm.at[i].add(jnp.moveaxis(val, axis, 0))
        return jnp.moveaxis(vm, 0, axis)
    return apply(fn, _coerce(x), _coerce(index), _coerce(value))


def index_put(x, indices, value, accumulate=False, name=None):
    idxs = [_coerce(i) for i in indices]
    def fn(v, val, *ids):
        at = v.at[tuple(ids)]
        return at.add(val) if accumulate else at.set(val)
    return apply(fn, _coerce(x), _coerce(value), *idxs)


def masked_select(x, mask, name=None):
    # dynamic output shape: host-side compute (not jittable; parity with
    # paddle's dynamic-shape op). Inside jit use where() instead.
    x = _coerce(x)
    m = _coerce(mask)
    vals = np.asarray(x._value)[np.asarray(m._value)]
    return Tensor(jnp.asarray(vals))


def masked_fill(x, mask, value, name=None):
    from .math import _scalarize
    return apply(lambda v, m, val: jnp.where(m, jnp.asarray(val, v.dtype), v),
                 _coerce(x), _coerce(mask), _scalarize(value))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    from .math import _scalarize
    return apply(lambda c, a, b: jnp.where(c, a, b),
                 _coerce(condition), _scalarize(x), _scalarize(y))


def nonzero(x, as_tuple=False):
    # dynamic shape → host-side (parity: paddle.nonzero is dynamic too)
    arr = np.asarray(_coerce(x)._value)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, dtype=dtypes.int64)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=dtypes.int64))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _coerce(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        # full-rank paddle format: [d0_l, d0_r, d1_l, d1_r, ...] ordered by dim
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # NCHW-style partial spec applies to trailing spatial dims, reversed
        # pairs (paddle uses [left, right, top, bottom] == last-dim-first)
        k = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC / NDHWC / NLC
            spatial = list(range(1, 1 + k))
        else:
            spatial = list(range(nd - k, nd))
        for j, d in enumerate(reversed(spatial)):
            width[d] = (pad[2 * j], pad[2 * j + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    kw = {"constant_values": value} if jmode == "constant" else {}
    return apply(lambda v: jnp.pad(v, width, mode=jmode, **kw), x)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._value)
        total = int(reps.sum())
        return apply(lambda v, r: jnp.repeat(v, r, axis=axis,
                                             total_repeat_length=total),
                     _coerce(x), repeats)
    return apply(lambda v: jnp.repeat(v, repeats, axis=axis), _coerce(x))


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax),
                 _coerce(x), _coerce(y))


def as_strided(x, shape, stride, offset=0, name=None):
    def fn(v):
        flat = v.reshape(-1)
        idx = offset + builtins_sum_outer(shape, stride)
        return flat[idx]
    def builtins_sum_outer(shape_, stride_):
        grids = jnp.meshgrid(*[jnp.arange(s) for s in shape_], indexing="ij")
        lin = 0
        for g, st in zip(grids, stride_):
            lin = lin + g * st
        return lin
    return apply(fn, _coerce(x))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply(lambda v: v.view(dtypes.convert_dtype(shape_or_dtype)), _coerce(x))


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, _coerce(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, _coerce(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, _coerce(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def cast(x, dtype, name=None):
    return _coerce(x).astype(dtype)


def slice(input, axes, starts, ends):
    def fn(v):
        out = v
        for ax, st, en in zip(axes, starts, ends):
            st = int(st.item()) if isinstance(st, Tensor) else int(st)
            en = int(en.item()) if isinstance(en, Tensor) else int(en)
            dim = v.shape[ax]
            st = max(st + dim, 0) if st < 0 else min(st, dim)
            en = max(en + dim, 0) if en < 0 else min(en, dim)
            out = jax.lax.slice_in_dim(out, st, en, axis=ax)
        return out
    return apply(fn, _coerce(input))


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins
    def fn(v):
        out = v
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx = [builtins.slice(None)] * out.ndim
            idx[ax] = builtins.slice(int(st), int(en), int(sd))
            out = out[tuple(idx)]
        return out
    return apply(fn, _coerce(x))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(v):
        size = (index_num + nshards - 1) // nshards
        lo = shard_id * size
        in_shard = (v >= lo) & (v < lo + size)
        return jnp.where(in_shard, v - lo, ignore_value)
    return apply(fn, _coerce(input))


def crop(x, shape=None, offsets=None, name=None):
    sh = _static_shape(shape)
    offs = [0] * len(sh) if offsets is None else [int(o) for o in offsets]
    def fn(v):
        return jax.lax.dynamic_slice(v, offs, sh)
    return apply(fn, _coerce(x))


def as_complex(x, name=None):
    """[..., 2] real pairs -> complex (parity: python/paddle/tensor/
    manipulation.py as_complex)."""
    return apply(lambda v: jax.lax.complex(v[..., 0], v[..., 1]),
                 _coerce(x))


def as_real(x, name=None):
    """complex -> [..., 2] real pairs (parity: python/paddle/tensor/
    manipulation.py as_real)."""
    return apply(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
                 _coerce(x))


def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis` (parity: python/paddle/tensor/
    manipulation.py unfold — the Tensor-level op, distinct from
    F.unfold/im2col). Output appends the window dim last."""
    ax = int(axis)
    sz = int(size)
    st = int(step)

    def fn(v):
        a = ax % v.ndim
        n = (v.shape[a] - sz) // st + 1
        starts = jnp.arange(n) * st
        idx = starts[:, None] + jnp.arange(sz)[None, :]        # [n, size]
        out = jnp.take(v, idx.reshape(-1), axis=a)
        new_shape = v.shape[:a] + (n, sz) + v.shape[a + 1:]
        out = out.reshape(new_shape)
        # paddle puts the window dim last
        return jnp.moveaxis(out, a + 1, -1)
    return apply(fn, _coerce(x))


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Out-of-place core of Tensor.fill_diagonal_ (parity:
    python/paddle/tensor/manipulation.py fill_diagonal_)."""
    def fn(v):
        if v.ndim == 2:
            h, w = v.shape
            ii = jnp.arange(h)[:, None]
            jj = jnp.arange(w)[None, :]
            if wrap and h > w:
                # numpy wrap rule: fill every (w+1)-th FLAT element, so
                # the diagonal restarts one row below after running off
                # the bottom
                flat = ii * w + jj
                mask = (flat - offset) % (w + 1) == 0
                return jnp.where(mask, jnp.asarray(value, v.dtype), v)
            mask = (jj - ii) == offset
            return jnp.where(mask, jnp.asarray(value, v.dtype), v)
        # n-dim: reference requires equal dims and no offset/wrap
        if len(set(v.shape)) != 1:
            raise ValueError(
                "fill_diagonal with ndim > 2 requires all dimensions "
                f"equal, got shape {v.shape}")
        if offset != 0 or wrap:
            raise ValueError(
                "fill_diagonal offset/wrap are 2-D only")
        idx = jnp.arange(v.shape[0])
        return v.at[tuple(idx for _ in range(v.ndim))].set(
            jnp.asarray(value, v.dtype))
    return apply(fn, _coerce(x))


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write tensor y onto the (dim1, dim2) diagonal of x (parity:
    python/paddle/tensor/manipulation.py fill_diagonal_tensor)."""
    d1, d2 = int(dim1), int(dim2)

    def fn(v, yv):
        nd = v.ndim
        a, b = d1 % nd, d2 % nd
        perm = [d for d in range(nd) if d not in (a, b)] + [a, b]
        inv = [perm.index(d) for d in range(nd)]
        vt = v.transpose(perm)                   # [..., H, W]
        h, w = vt.shape[-2], vt.shape[-1]
        n = min(h, w - offset) if offset >= 0 else min(h + offset, w)
        ii = jnp.arange(n) + (0 if offset >= 0 else -offset)
        jj = jnp.arange(n) + (offset if offset >= 0 else 0)
        # y already carries the diagonal as its last axis
        vt = vt.at[..., ii, jj].set(yv)
        return vt.transpose(inv)
    return apply(fn, _coerce(x), _coerce(y))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto the (axis1, axis2) diagonal (parity:
    python/paddle/tensor/manipulation.py diagonal_scatter)."""
    return fill_diagonal_tensor(x, y, offset=offset, dim1=axis1,
                                dim2=axis2)


def matrix_transpose(x, name=None):
    """Swap the last two dims (parity: paddle Tensor.mT /
    matrix_transpose)."""
    return apply(lambda v: jnp.swapaxes(v, -1, -2), _coerce(x))
