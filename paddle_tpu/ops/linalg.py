"""Linear algebra ops (parity: python/paddle/tensor/linalg.py).

matmul lowers straight to jnp.matmul → XLA dot_general → the MXU. This is
the op that replaces phi::MatmulKernel<GPU> (paddle/phi/kernels/gpu/ via
cuBLAS); on TPU keeping everything as dot_general lets XLA tile onto the
systolic array and fuse epilogues.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ._dispatch import apply
from .creation import _coerce


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return apply(fn, _coerce(x), _coerce(y), _name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return apply(fn, _coerce(x), _coerce(y))


def mv(x, vec, name=None):
    return apply(lambda a, v: a @ v, _coerce(x), _coerce(vec))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b),
                 _coerce(input), _coerce(x), _coerce(y))


def multi_dot(x, name=None):
    ts = [_coerce(t) for t in x]
    return apply(lambda *vs: jnp.linalg.multi_dot(vs), *ts)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = _coerce(x)
    def fn(v):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.real(v * jnp.conj(v))))
            return jnp.linalg.norm(v, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=_ax(axis), keepdims=keepdim)
        if p == float("inf") or p == "inf":
            if axis is None:
                return jnp.max(jnp.abs(v))
            return jnp.linalg.norm(v, ord=np.inf, axis=_ax(axis), keepdims=keepdim)
        if p == float("-inf") or p == "-inf":
            if axis is None:
                return jnp.min(jnp.abs(v))
            return jnp.linalg.norm(v, ord=-np.inf, axis=_ax(axis), keepdims=keepdim)
        if axis is None:
            return jnp.sum(jnp.abs(v) ** p) ** (1.0 / p)
        return jnp.linalg.norm(v, ord=p, axis=_ax(axis), keepdims=keepdim)
    return apply(fn, x)


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.linalg.vector_norm(v, ord=p, axis=_ax(axis),
                                                  keepdims=keepdim), _coerce(x))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply(lambda v: jnp.linalg.matrix_norm(v, ord=p, keepdims=keepdim),
                 _coerce(x))


def dist(x, y, p=2, name=None):
    return apply(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p),
                 _coerce(x), _coerce(y))


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def fn(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, axis=-1))
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return apply(fn, _coerce(x), _coerce(y))


def cholesky(x, upper=False, name=None):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply(fn, _coerce(x))


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)
    return apply(fn, _coerce(x), _coerce(y))


def qr(x, mode="reduced", name=None):
    return apply(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), _coerce(x))


def svd(x, full_matrices=False, name=None):
    """Parity: paddle.linalg.svd returns (U, S, VH) with
    x = U @ diag(S) @ VH — VH, not V (the doc's third output is named
    vh; r5 fuzz find: the old V-transposed return broke
    reconstruction for every consumer following the upstream
    contract)."""
    def fn(v):
        return jnp.linalg.svd(v, full_matrices=full_matrices)
    return apply(fn, _coerce(x))


def svdvals(x, name=None):
    return apply(lambda v: jnp.linalg.svd(v, compute_uv=False), _coerce(x))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = _coerce(x)
    qq = q if q is not None else min(6, x._value.shape[-2], x._value.shape[-1])
    def fn(v):
        if center:
            v = v - v.mean(axis=-2, keepdims=True)
        u, s, vh = jnp.linalg.svd(v, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vh, -1, -2)[..., :qq]
    return apply(fn, x)


def inv(x, name=None):
    return apply(jnp.linalg.inv, _coerce(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
                 _coerce(x))


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, _coerce(x), _coerce(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(fn, _coerce(x), _coerce(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rk, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rk, sv
    return apply(fn, _coerce(x), _coerce(y))


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
    out = apply(fn, _coerce(x))
    if get_infos:
        info = Tensor(jnp.zeros((), jnp.int32))
        return out[0], out[1], info
    return out


def eig(x, name=None):
    return apply(lambda v: tuple(np_eig(v)), _coerce(x))


def np_eig(v):
    # jnp.linalg.eig is CPU-only in jax; route via callback for parity
    import jax.numpy as jnp_
    vals, vecs = np.linalg.eig(np.asarray(v))
    return jnp_.asarray(vals), jnp_.asarray(vecs)


def eigh(x, UPLO="L", name=None):
    return apply(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), _coerce(x))


def eigvals(x, name=None):
    def fn(v):
        vals = np.linalg.eigvals(np.asarray(v))
        return jnp.asarray(vals)
    return apply(fn, _coerce(x))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), _coerce(x))


def det(x, name=None):
    return apply(jnp.linalg.det, _coerce(x))


def slogdet(x, name=None):
    return apply(lambda v: tuple(jnp.linalg.slogdet(v)), _coerce(x))


def matrix_power(x, n, name=None):
    return apply(lambda v: jnp.linalg.matrix_power(v, n), _coerce(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.matrix_rank(v, rtol=tol), _coerce(x))


def matrix_exp(x, name=None):
    return apply(jax.scipy.linalg.expm, _coerce(x))


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                                 jnp.ones(a.shape[:-2] + (1,), a.dtype),
                                 a[..., i + 1:, i]], axis=-1)
            ti = t[..., i:i + 1, None]
            q = q - ti * (q @ v[..., :, None]) @ v[..., None, :]
        return q[..., :, :n]
    return apply(fn, _coerce(x), _coerce(tau))


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda v: jnp.corrcoef(v, rowvar=rowvar), _coerce(x))


def cond(x, p=None, name=None):
    """Condition number (parity: python/paddle/tensor/linalg.py cond)."""
    def fn(v):
        pp = 2 if p is None else p
        if pp in ("fro", "nuc") or isinstance(pp, (int, float)):
            return jnp.linalg.cond(v, p=None if pp == 2 else pp)
        raise ValueError(f"unsupported norm order {p}")
    return apply(fn, _coerce(x))


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack the packed LU factor + 1-based pivots from `lu` into P, L, U
    (parity: python/paddle/tensor/linalg.py lu_unpack)."""
    def fn(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        if unpack_ludata:
            tril = jnp.tril(lu_[..., :, :k], k=-1)
            eye = jnp.eye(m, k, dtype=lu_.dtype)
            l = tril + jnp.broadcast_to(eye, tril.shape)
            u = jnp.triu(lu_[..., :k, :])
        else:
            l = jnp.zeros(lu_.shape[:-2] + (m, k), lu_.dtype)
            u = jnp.zeros(lu_.shape[:-2] + (k, n), lu_.dtype)
        # pivots (1-based sequential row swaps) -> permutation, applied
        # inside a fori_loop so the traced graph is O(1) in matrix size
        perm = jnp.broadcast_to(jnp.arange(m), piv.shape[:-1] + (m,))
        npiv = piv.shape[-1]

        def body(i, pm):
            j = piv[..., i] - 1                            # [...] int
            ii = jnp.broadcast_to(i, pm.shape[:-1] + (1,))
            jj = j[..., None] if pm.ndim > 1 else j[None]
            pi = jnp.take_along_axis(pm, ii, axis=-1)
            pj = jnp.take_along_axis(pm, jj, axis=-1)
            pm = jnp.put_along_axis(pm, ii, pj, axis=-1, inplace=False)
            return jnp.put_along_axis(pm, jj, pi, axis=-1, inplace=False)

        perm = jax.lax.fori_loop(0, npiv, body, perm)
        p = jax.nn.one_hot(perm, m, dtype=lu_.dtype)
        p = jnp.swapaxes(p, -1, -2)
        return p, l, u
    return apply(fn, _coerce(x), _coerce(y))


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by Q from a QR factorization held as Householder
    reflectors (parity: python/paddle/tensor/linalg.py ormqr)."""
    def fn(a, t, c):
        # build Q explicitly (m x m) from reflectors, then contract —
        # XLA-friendly (static shapes, batched matmul on the MXU). The
        # reflector loop runs in a fori_loop with masked full-width
        # columns so the traced graph is O(1) in reflector count.
        m = a.shape[-2]
        nref = t.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q0 = jnp.broadcast_to(eye, a.shape[:-2] + (m, m))
        rows = jnp.arange(m)

        def body(i, q):
            col = jnp.take_along_axis(
                a, jnp.broadcast_to(i, a.shape[:-2] + (m, 1)),
                axis=-1)[..., 0]                            # a[..., :, i]
            v = jnp.where(rows == i, jnp.asarray(1, a.dtype),
                          jnp.where(rows > i, col, 0))
            ti = jnp.take_along_axis(
                t, jnp.broadcast_to(i, t.shape[:-1] + (1,)),
                axis=-1)[..., None]                         # t[..., i]
            return q - ti * (q @ v[..., :, None]) @ v[..., None, :]

        q = jax.lax.fori_loop(0, nref, body, q0)
        if transpose:
            q = jnp.swapaxes(q, -1, -2)
        return q @ c if left else c @ q
    return apply(fn, _coerce(x), _coerce(tau), _coerce(other))


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (parity: python/paddle/tensor/linalg.py
    svd_lowrank; Halko et al. subspace iteration)."""
    from ..framework.random import next_key
    key = next_key()
    args = [_coerce(x)]
    if M is not None:
        args.append(_coerce(M))

    def fn(v, *rest):
        a = v - rest[0] if rest else v
        m, n = a.shape[-2], a.shape[-1]
        r = min(q, m, n)
        omega = jax.random.normal(key, a.shape[:-2] + (n, r), dtype=a.dtype)
        y = a @ omega
        qmat, _ = jnp.linalg.qr(y)
        for _ in range(niter):
            z = jnp.swapaxes(a, -1, -2) @ qmat
            qz, _ = jnp.linalg.qr(z)
            y = a @ qz
            qmat, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qmat, -1, -2) @ a
        u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
        u = qmat @ u_b
        return u, s, jnp.swapaxes(vh, -1, -2)
    return apply(fn, *args)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    """Alias of paddle.cov under paddle.linalg (parity:
    python/paddle/tensor/linalg.py re-export)."""
    from .math import cov as _cov
    return _cov(x, rowvar=rowvar, ddof=ddof, fweights=fweights,
                aweights=aweights, name=name)


def matrix_transpose(x, name=None):
    """Swap the last two dims (parity: paddle.linalg.matrix_transpose)."""
    from ._dispatch import apply as _apply
    return _apply(lambda v: jnp.swapaxes(v, -2, -1), x,
                  _name="matrix_transpose")


def vecdot(x, y, axis=-1, name=None):
    """Vector dot along `axis` with conjugation of x (parity:
    paddle.linalg.vecdot)."""
    from ._dispatch import apply as _apply
    from .creation import _coerce
    return _apply(lambda a, b: jnp.sum(jnp.conj(a) * b, axis=axis),
                  _coerce(x), _coerce(y), _name="vecdot")
