"""Op library aggregation + Tensor method patching.

Reference parity: Paddle assembles `paddle.*` from python/paddle/tensor/*
and monkey-patches the methods onto `paddle.Tensor`
(python/paddle/tensor/__init__.py::tensor_method_func list). Same approach
here: every op taking a leading Tensor also becomes a Tensor method, plus
the arithmetic dunders and `op_` in-place variants.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from . import _dispatch
from ._dispatch import apply
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403

from . import creation, math, manipulation, logic, linalg, random, search
from . import extras
from .creation import _coerce

# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

def _norm_index_component(i):
    """Resolve Tensor components inside slices to python ints."""
    if isinstance(i, builtins.slice):
        def g(v):
            return int(v.item()) if isinstance(v, Tensor) else v
        return builtins.slice(g(i.start), g(i.stop), g(i.step))
    return i


def _tensor_getitem(self: Tensor, item):
    items = item if isinstance(item, tuple) else (item,)
    items = tuple(_norm_index_component(i) for i in items)
    tensor_idx = [i for i in items if isinstance(i, Tensor)]

    def fn(v, *idx_arrays):
        it = iter(idx_arrays)
        resolved = tuple(next(it) if isinstance(i, Tensor) else i for i in items)
        return v[resolved]

    return apply(fn, self, *tensor_idx, _name="getitem")


def _tensor_setitem(self: Tensor, item, value):
    from ..autograd.grad_mode import is_grad_enabled
    if is_grad_enabled() and not self.stop_gradient and self.is_leaf:
        raise RuntimeError(
            "setitem on a leaf Tensor that requires grad; wrap in "
            "paddle.no_grad()")
    items = item if isinstance(item, tuple) else (item,)
    items = tuple(_norm_index_component(i) for i in items)
    tensor_idx = [i for i in items if isinstance(i, Tensor)]
    val = value if isinstance(value, Tensor) else Tensor(jnp.asarray(value))

    def fn(v, valv, *idx_arrays):
        it = iter(idx_arrays)
        resolved = tuple(next(it) if isinstance(i, Tensor) else i for i in items)
        return v.at[resolved].set(valv.astype(v.dtype))

    self._inplace_update(apply(fn, self, val, *tensor_idx, _name="setitem"))


Tensor.__getitem__ = _tensor_getitem
Tensor.__setitem__ = _tensor_setitem

# ---------------------------------------------------------------------------
# dunders
# ---------------------------------------------------------------------------

def _rev(fn):
    def r(self, other):
        return fn(other, self)
    return r


Tensor.__add__ = lambda s, o: math.add(s, o)
Tensor.__radd__ = lambda s, o: math.add(o, s)
Tensor.__sub__ = lambda s, o: math.subtract(s, o)
Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
Tensor.__mul__ = lambda s, o: math.multiply(s, o)
Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
Tensor.__truediv__ = lambda s, o: math.divide(s, o)
Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
Tensor.__mod__ = lambda s, o: math.remainder(s, o)
Tensor.__rmod__ = lambda s, o: math.remainder(o, s)
Tensor.__pow__ = lambda s, o: math.pow(s, o)
Tensor.__rpow__ = lambda s, o: math.pow(o, s)
Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
Tensor.__neg__ = lambda s: math.neg(s)
Tensor.__abs__ = lambda s: math.abs(s)
Tensor.__invert__ = lambda s: logic.logical_not(s) if s.dtype == jnp.bool_ else logic.bitwise_not(s)
Tensor.__and__ = lambda s, o: logic.logical_and(s, o) if s.dtype == jnp.bool_ else logic.bitwise_and(s, o)
Tensor.__or__ = lambda s, o: logic.logical_or(s, o) if s.dtype == jnp.bool_ else logic.bitwise_or(s, o)
Tensor.__xor__ = lambda s, o: logic.logical_xor(s, o) if s.dtype == jnp.bool_ else logic.bitwise_xor(s, o)
Tensor.__eq__ = lambda s, o: logic.equal(s, o)
Tensor.__ne__ = lambda s, o: logic.not_equal(s, o)
Tensor.__lt__ = lambda s, o: logic.less_than(s, o)
Tensor.__le__ = lambda s, o: logic.less_equal(s, o)
Tensor.__gt__ = lambda s, o: logic.greater_than(s, o)
Tensor.__ge__ = lambda s, o: logic.greater_equal(s, o)
Tensor.__hash__ = lambda s: id(s)
Tensor.__lshift__ = lambda s, o: logic.bitwise_left_shift(s, o)
Tensor.__rlshift__ = lambda s, o: logic.bitwise_left_shift(o, s)
Tensor.__rshift__ = lambda s, o: logic.bitwise_right_shift(s, o)
Tensor.__rrshift__ = lambda s, o: logic.bitwise_right_shift(o, s)


def _tensor_divmod(s, o):
    return apply(jnp.divmod, _coerce(s), _coerce(o), _name="divmod")


Tensor.__divmod__ = _tensor_divmod
Tensor.__rdivmod__ = lambda s, o: _tensor_divmod(o, s)


def _tensor_iter(self):
    # without __iter__, python's fallback loops __getitem__(0, 1, ...)
    # forever (jax indexing clamps out-of-range instead of raising);
    # the ndim check must run EAGERLY, not inside the generator
    if self.ndim == 0:
        raise TypeError("iteration over a 0-D tensor")

    def gen():
        for i in range(self._value.shape[0]):
            yield self[i]

    return gen()


def _tensor_contains(self, item):
    return bool(jnp.any(self._value == _coerce(item)._value))


Tensor.__iter__ = _tensor_iter
Tensor.__contains__ = _tensor_contains


def _tensor_dlpack(self, *a, **kw):
    return self._value.__dlpack__(*a, **kw)


def _tensor_dlpack_device(self):
    return self._value.__dlpack_device__()


Tensor.__dlpack__ = _tensor_dlpack
Tensor.__dlpack_device__ = _tensor_dlpack_device

# ---------------------------------------------------------------------------
# method attachment
# ---------------------------------------------------------------------------

_METHOD_SOURCES = [creation, math, manipulation, logic, linalg, search,
                   random, extras]

# names whose first parameter is NOT a tensor (skip for method patching)
_SKIP = {
    "zeros", "ones", "full", "empty", "arange", "linspace", "logspace", "eye",
    "meshgrid", "rand", "randn", "randint", "randperm", "uniform", "normal",
    "gaussian", "standard_normal", "tril_indices", "triu_indices",
    "scatter_nd", "to_tensor", "broadcast_shape", "assign", "einsum",
    "add_n", "multi_dot", "broadcast_tensors", "multiplex", "log_normal",
    "searchsorted", "complex", "polar", "binomial",
    "hstack", "vstack", "dstack", "column_stack", "row_stack",
    "block_diag",
}

_patched = set()
_CLASS_ATTRS = set(dir(Tensor))  # never shadow properties/methods of Tensor
for _mod in _METHOD_SOURCES:
    for _name in dir(_mod):
        if (_name.startswith("_") or _name in _SKIP or _name in _patched
                or _name in _CLASS_ATTRS):
            continue
        _fn = getattr(_mod, _name)
        if not callable(_fn) or isinstance(_fn, type):
            continue
        if getattr(_fn, "__module__", "").startswith("jax"):
            continue
        setattr(Tensor, _name, _fn)
        _patched.add(_name)

# searchsorted-as-method has tensor-first semantics via bucketize
Tensor.bucketize = lambda self, ss, **kw: search.bucketize(self, ss, **kw)

# ---------------------------------------------------------------------------
# in-place variants (parity: paddle's `op_` API family)
# ---------------------------------------------------------------------------

def _make_inplace(fn):
    def op_(self, *a, **kw):
        self._check_inplace()
        return self._inplace_update(fn(self, *a, **kw))
    return op_


_INPLACE = {
    "add_": math.add, "subtract_": math.subtract, "multiply_": math.multiply,
    "divide_": math.divide, "scale_": math.scale, "clip_": math.clip,
    "exp_": math.exp, "sqrt_": math.sqrt, "rsqrt_": math.rsqrt,
    "reciprocal_": math.reciprocal, "floor_": math.floor, "ceil_": math.ceil,
    "round_": math.round, "abs_": math.abs, "tanh_": math.tanh,
    "neg_": math.neg, "sigmoid_": None,  # filled by nn.functional later
    "remainder_": math.remainder, "pow_": math.pow,
    "cast_": manipulation.cast, "flatten_": manipulation.flatten,
    "fill_": None, "zero_": None,
}

for _n, _f in _INPLACE.items():
    if _f is not None:
        setattr(Tensor, _n, _make_inplace(_f))
        _patched.add(_n)


def _fill_(self, value):
    self._value = jnp.full(self._value.shape, value, self._value.dtype)
    return self


def _zero_(self):
    self._value = jnp.zeros(self._value.shape, self._value.dtype)
    return self


Tensor.fill_ = _fill_
Tensor.zero_ = _zero_


# second batch of in-place variants (the long tail paddle exposes)
_INPLACE2 = {
    "log_": math.log, "log2_": math.log2, "log10_": math.log10,
    "log1p_": math.log1p, "expm1_": math.expm1,
    "sin_": math.sin, "cos_": math.cos, "erfinv_": math.erfinv,
    "lerp_": math.lerp, "mod_": math.mod, "trunc_": math.trunc,
    "renorm_": extras.renorm, "t_": manipulation.t,
    "index_fill_": extras.index_fill,
    "masked_fill_": manipulation.masked_fill,
    "put_along_axis_": manipulation.put_along_axis,
    "index_put_": manipulation.index_put,
    "fill_diagonal_": manipulation.fill_diagonal,
    "fill_diagonal_tensor_": manipulation.fill_diagonal_tensor,
}
for _n, _f in _INPLACE2.items():
    setattr(Tensor, _n, _make_inplace(_f))
    _patched.add(_n)

Tensor.fill_diagonal_tensor = manipulation.fill_diagonal_tensor


def _sigmoid_(self):
    self._check_inplace()
    import jax.nn as _jnn
    return self._inplace_update(apply(_jnn.sigmoid, self))


def _relu_(self):
    self._check_inplace()
    import jax.nn as _jnn
    return self._inplace_update(apply(_jnn.relu, self))


Tensor.sigmoid_ = _sigmoid_
Tensor.relu_ = _relu_

# small introspection methods (parity: pybind eager_method.cc)
Tensor.element_size = lambda self: self._value.dtype.itemsize
Tensor.nbytes = property(lambda self: self._value.nbytes)
Tensor.ndimension = lambda self: self._value.ndim
Tensor.dim = lambda self: self._value.ndim


def _retain_grads(self):
    """Non-leaf tensors keep .grad after backward (parity:
    Tensor.retain_grads). The tape stores grads for any tensor with
    _retain flag set."""
    self._retain_grad = True
    return self


Tensor.retain_grads = _retain_grads


# third batch of in-place variants
for _n, _f in {"index_add_": extras.index_add
               if hasattr(extras, "index_add") else None,
               "index_put_": manipulation.index_put,
               "masked_scatter_": manipulation.masked_scatter
               if hasattr(manipulation, "masked_scatter") else None,
               "diagonal_scatter_": manipulation.diagonal_scatter}.items():
    if _f is not None:
        setattr(Tensor, _n, _make_inplace(_f))
        _patched.add(_n)


# fourth batch: remaining documented in-place variants + top-level aliases
for _n, _f in {"square_": math.square, "frac_": math.frac,
               "hypot_": math.hypot, "ldexp_": extras.ldexp,
               "gammaln_": extras.gammaln, "i0_": math.i0}.items():
    setattr(Tensor, _n, _make_inplace(_f))
    _patched.add(_n)

Tensor.bitwise_invert = logic.bitwise_not
Tensor.bitwise_invert_ = _make_inplace(logic.bitwise_not)

# top-level in-place function aliases (parity: python/paddle/tensor/ops.py
# *_-suffixed exports)
bitwise_invert = logic.bitwise_not


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """input*beta + alpha*(x @ y) over batched matrices (parity:
    python/paddle/tensor/math.py baddbmm)."""
    from .creation import _coerce as _c
    return apply(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                 _c(input), _c(x), _c(y), _name="baddbmm")


Tensor.baddbmm = baddbmm
Tensor.baddbmm_ = _make_inplace(baddbmm)


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (parity: python/paddle/tensor/math.py
    reduce_as) — the broadcast-inverse reduction."""
    from .creation import _coerce as _c
    x = _c(x)
    tshape = tuple(int(s) for s in
                   (target.shape if hasattr(target, "shape") else target))

    def fn(v):
        extra = v.ndim - len(tshape)
        axes = list(range(extra))
        for i, ts in enumerate(tshape):
            if v.shape[extra + i] != ts:
                axes.append(extra + i)
        out = jnp.sum(v, axis=tuple(axes), keepdims=True)
        return out.reshape(tshape)
    return apply(fn, x, _name="reduce_as")


Tensor.reduce_as = reduce_as


def tolist(x):
    """Parity: paddle.tolist (python/paddle/tensor/to_string.py)."""
    return x.tolist()


# contiguity / storage introspection parity (pybind eager_method.cc):
# XLA arrays are always dense row-major from the API's viewpoint
Tensor.is_contiguous = lambda self: True
Tensor.contiguous = lambda self: self


def _strides(self):
    """Row-major element strides (parity: Tensor.strides)."""
    shape = self.shape
    out = [1] * len(shape)
    for i in range(len(shape) - 2, -1, -1):
        out[i] = out[i + 1] * int(shape[i + 1])
    return out


Tensor.strides = property(_strides)
Tensor.get_strides = _strides


def _data_ptr(self):
    """Device buffer address (parity: Tensor.data_ptr). Best-effort:
    jax exposes it for committed device arrays; tracers have none."""
    v = self._value
    try:
        return v.unsafe_buffer_pointer()
    except (AttributeError, NotImplementedError) as e:
        raise RuntimeError(f"data_ptr unavailable: {e}") from e


def _set_data(self, value):
    """Paddle's Tensor.data is settable (weight surgery / EMA updates):
    assignment rebinds this tensor's value in place."""
    self._inplace_update(value if isinstance(value, Tensor)
                         else Tensor(jnp.asarray(value)))


Tensor.data_ptr = _data_ptr
# legacy accessors: the eager Tensor IS its own data/DenseTensor here
Tensor.data = property(lambda self: self, _set_data)
Tensor.value = lambda self: self
Tensor.get_tensor = lambda self: self


# ---------------------------------------------------------------------------
# surface tail (round 4): aliases, module-level in-place exports, and the
# remaining small ops ported code reaches for (reference:
# python/paddle/tensor/__init__.py name inventory)
# ---------------------------------------------------------------------------

absolute = math.abs                       # paddle.absolute == paddle.abs
less = logic.less_than                    # alias pair of less_than
reverse = manipulation.flip               # legacy name for flip


def sigmoid(x, name=None):
    import jax.nn as _jnn
    return apply(_jnn.sigmoid, x, _name="sigmoid")


def fliplr(x, name=None):
    """Flip along dim 1 (parity: paddle.fliplr; requires ndim >= 2)."""
    return manipulation.flip(x, axis=1)


def flipud(x, name=None):
    """Flip along dim 0 (parity: paddle.flipud)."""
    return manipulation.flip(x, axis=0)


def vdot(x, y, name=None):
    """Flattened conj-dot (parity: paddle.vdot / torch.vdot)."""
    def fn(a, b):
        return jnp.vdot(a, b)
    return apply(fn, x, y, _name="vdot")


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    """input + value * tensor1 * tensor2 (parity: paddle.addcmul)."""
    def fn(a, t1, t2):
        return a + value * t1 * t2
    return apply(fn, input, tensor1, tensor2, _name="addcmul")


def addcdiv(input, tensor1, tensor2, value=1.0, name=None):
    """input + value * tensor1 / tensor2 (parity: paddle.addcdiv)."""
    def fn(a, t1, t2):
        return a + value * t1 / t2
    return apply(fn, input, tensor1, tensor2, _name="addcdiv")


def chain_matmul(*mats, name=None):
    """Chained matmul of 2-D tensors (parity: legacy chain_matmul)."""
    if len(mats) == 1 and isinstance(mats[0], (list, tuple)):
        mats = tuple(mats[0])
    out = mats[0]
    for m in mats[1:]:
        out = linalg.matmul(out, m)
    return out


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (parity:
    paddle.cholesky_inverse)."""
    def fn(l):
        import jax.scipy.linalg as jsl
        eye = jnp.eye(l.shape[-1], dtype=l.dtype)
        # jsl.cho_solve takes `lower`; paddle's flag is `upper`
        return jsl.cho_solve((l, not upper), eye)
    return apply(fn, x, _name="cholesky_inverse")


def nonzero_static(x, size, fill_value=-1, name=None):
    """Static-shape nonzero (parity: paddle.nonzero_static): returns
    [size, ndim] indices padded/truncated with fill_value — the
    jit-compatible form (dynamic nonzero cannot live under jit)."""
    def fn(v):
        idx = jnp.nonzero(v, size=int(size), fill_value=None)
        # jnp fills out-of-range with the last valid index; rebuild the
        # paddle fill semantics from the true count
        n = jnp.sum((v != 0).astype(jnp.int64))
        stacked = jnp.stack(idx, axis=1).astype(jnp.int64)
        live = jnp.arange(int(size))[:, None] < n
        return jnp.where(live, stacked, jnp.int64(fill_value))
    return apply(fn, x, _name="nonzero_static")


def _log_normal_(self, mean=1.0, std=2.0, shape=None, name=None):
    """In-place log-normal fill (parity: Tensor.log_normal_)."""
    self._check_inplace()
    from ..framework.random import next_key
    import jax.random as jrandom

    def fn(v):
        k = next_key()
        return jnp.exp(mean + std * jrandom.normal(k, v.shape,
                                                   jnp.float32)
                       ).astype(v.dtype)
    return self._inplace_update(apply(fn, self, _name="log_normal_"))


Tensor.log_normal_ = _log_normal_

# remaining Tensor in-place methods the reference exposes
_INPLACE3 = {
    "tan_": math.tan, "tril_": creation.tril, "triu_": creation.triu,
    "masked_scatter_": extras.masked_scatter,
    "index_add_": (lambda self, index, axis, value:
                   manipulation.index_add(self, index, axis, value)),
}
for _n, _f in _INPLACE3.items():
    setattr(Tensor, _n, _make_inplace(_f))
    _patched.add(_n)


def _module_inplace(name):
    def fn(x, *a, **kw):
        return getattr(x, name)(*a, **kw)
    fn.__name__ = name
    return fn


# module-level in-place exports (paddle.sin_(x) etc. mirror Tensor.sin_)
for _n in ("sin_", "cos_", "tan_", "pow_", "mod_", "tril_", "triu_",
           "index_add_", "index_fill_", "index_put_", "masked_fill_",
           "masked_scatter_", "fill_diagonal_", "flatten_", "sigmoid_",
           "log_normal_", "lerp_", "erfinv_", "trunc_", "renorm_",
           "add_", "subtract_", "multiply_", "divide_", "exp_", "sqrt_",
           "rsqrt_", "reciprocal_", "floor_", "ceil_", "round_", "abs_",
           "neg_", "remainder_", "cast_", "fill_", "zero_", "t_",
           "scale_", "clip_", "tanh_", "square_", "frac_",
           "log_", "log2_", "log10_", "log1p_", "expm1_",
           "hypot_", "ldexp_", "gammaln_", "i0_"):
    globals().setdefault(_n, _module_inplace(_n))

# matrix-view properties (parity: paddle.Tensor.T reverses ALL axes;
# Tensor.mT swaps the trailing two — python/paddle/tensor/attribute.py)
Tensor.T = property(lambda self: manipulation.transpose(
    self, perm=list(range(self.ndim))[::-1]) if self.ndim >= 2 else self)
Tensor.mT = property(lambda self: manipulation.transpose(
    self, perm=list(range(self.ndim - 2)) + [self.ndim - 1, self.ndim - 2]))
Tensor.sigmoid = sigmoid
