"""Random ops over the global stateful generator
(parity: python/paddle/tensor/random.py; generator semantics from
paddle/phi/core/generator.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework import dtype as dtypes
from ..framework.random import next_key
from .creation import _shape, _coerce
from ._dispatch import apply


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else (default or dtypes.get_default_dtype())


def rand(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    x._value = jax.random.uniform(next_key(), x._value.shape, x._value.dtype,
                                  minval=min, maxval=max)
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(next_key(), sh) * s + m)
    sh = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(next_key(), sh,
                                    dtypes.get_default_dtype()) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    x._value = (jax.random.normal(next_key(), x._value.shape, x._value.dtype)
                * std + mean)
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None) -> Tensor:
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)) * std + mean)


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     _dt(dtype, dtypes.int64)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    x = _coerce(x)
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), tuple(x._value.shape), low,
                                     high, _dt(dtype, x.dtype)))


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(_dt(dtype, dtypes.int64)))


def shuffle(x, name=None) -> Tensor:
    x = _coerce(x)
    perm = jax.random.permutation(next_key(), x._value.shape[0])
    return apply(lambda v: v[perm], x)


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    x = _coerce(x)
    def draw(v):
        logits = jnp.log(jnp.maximum(v, 1e-38))
        if replacement:
            return jax.random.categorical(
                next_key(), logits, axis=-1,
                shape=(num_samples,) + v.shape[:-1]).T if v.ndim > 1 else \
                jax.random.categorical(next_key(), logits, shape=(num_samples,))
        # without replacement: gumbel top-k trick
        g = jax.random.gumbel(next_key(), v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    return Tensor(draw(x._value).astype(dtypes.int64))


def bernoulli(x, name=None) -> Tensor:
    x = _coerce(x)
    u = jax.random.uniform(next_key(), tuple(x._value.shape))
    return apply(lambda v: (u < v).astype(v.dtype), x)


def bernoulli_(x, p=0.5, name=None) -> Tensor:
    x._value = (jax.random.uniform(next_key(), x._value.shape) < p).astype(x._value.dtype)
    return x


def poisson(x, name=None) -> Tensor:
    x = _coerce(x)
    return Tensor(jax.random.poisson(next_key(), x._value).astype(x.dtype))


def binomial(count, prob, name=None) -> Tensor:
    c = _coerce(count)
    p = _coerce(prob)
    return Tensor(jax.random.binomial(next_key(), c._value.astype(jnp.float32),
                                      p._value).astype(dtypes.int64))


def exponential_(x, lam=1.0, name=None) -> Tensor:
    x._value = (jax.random.exponential(next_key(), x._value.shape,
                                       x._value.dtype) / lam)
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None) -> Tensor:
    sh = _shape(shape) if shape is not None else ()
    return Tensor(jnp.exp(jax.random.normal(next_key(), sh,
                                            dtypes.get_default_dtype()) * std + mean))


def rand_like(x, dtype=None, name=None) -> Tensor:
    x = _coerce(x)
    return Tensor(jax.random.uniform(next_key(), tuple(x._value.shape),
                                     _dt(dtype, x.dtype)))


def randn_like(x, dtype=None, name=None) -> Tensor:
    x = _coerce(x)
    return Tensor(jax.random.normal(next_key(), tuple(x._value.shape),
                                    _dt(dtype, x.dtype)))


def standard_gamma(x, name=None) -> Tensor:
    """Sample Gamma(alpha=x, 1) (parity: paddle.standard_gamma)."""
    x = _coerce(x)
    return Tensor(jax.random.gamma(next_key(), x._value).astype(x.dtype))


def standard_exponential(x, name=None) -> Tensor:
    """Sample Exp(1) in x's shape (parity: paddle.standard_exponential)."""
    x = _coerce(x)
    return Tensor(jax.random.exponential(next_key(), x._value.shape,
                                         x._value.dtype))


def cauchy_(x, loc=0, scale=1, name=None) -> Tensor:
    """In-place standard-Cauchy fill (parity: paddle.Tensor.cauchy_)."""
    x._value = (loc + scale * jax.random.cauchy(
        next_key(), x._value.shape, x._value.dtype)).astype(x._value.dtype)
    return x


def geometric_(x, probs=0.5, name=None) -> Tensor:
    """In-place geometric fill (number of Bernoulli(p) trials until the
    first success, support {1, 2, ...} — paddle.Tensor.geometric_)."""
    u = jax.random.uniform(next_key(), x._value.shape)
    import numpy as _np
    k = jnp.ceil(jnp.log1p(-u) / _np.log1p(-probs))
    x._value = jnp.maximum(k, 1.0).astype(x._value.dtype)
    return x
