"""Shared spatial sampling primitives.

One home for the zero-padded / clamped bilinear gather+lerp used by
grid_sample (nn/functional_extra.py), roi_align and deform_conv2d
(vision/ops.py) — the three reference CUDA kernels
(grid_sample_kernel.cu, roi_align_kernel.cu, deformable_conv_kernel.cu)
share the same bilinear_interpolate device function, and so do we.
All helpers take a single feature map [C, H, W] and flat float coord
vectors [P]; batch/roi dimensions are vmapped by the callers (XLA fuses
the vmapped gathers into one batched gather).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def gather_zeros(fmap, yi, xi):
    """fmap[:, yi, xi] with 0 for out-of-range integer coords.
    fmap: [C, H, W]; yi/xi: int [P] -> [C, P]."""
    c, h, w = fmap.shape
    inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    yc = jnp.clip(yi, 0, h - 1)
    xc = jnp.clip(xi, 0, w - 1)
    out = fmap[:, yc, xc]
    return jnp.where(inside[None, :], out, 0)


def bilinear_zeros(fmap, ys, xs):
    """Zero-padding bilinear: out-of-range neighbors contribute 0 (the
    im2col convention of deformable conv / grid_sample padding_mode=
    'zeros'). fmap: [C, H, W]; ys/xs: float [P] -> [C, P]."""
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1, x1 = y0 + 1, x0 + 1
    wy = ys - y0
    wx = xs - x0
    return (gather_zeros(fmap, y0, x0) * ((1 - wy) * (1 - wx))[None]
            + gather_zeros(fmap, y0, x1) * ((1 - wy) * wx)[None]
            + gather_zeros(fmap, y1, x0) * (wy * (1 - wx))[None]
            + gather_zeros(fmap, y1, x1) * (wy * wx)[None])


def bilinear_clamped(fmap, ys, xs):
    """RoI-align convention (phi roi_align bilinear_interpolate): points
    outside [-1, size] sample 0; otherwise coords clamp to the border
    before interpolating. fmap: [C, H, W]; ys/xs: float [P] -> [C, P]."""
    c, h, w = fmap.shape
    valid = (ys >= -1.0) & (ys <= h) & (xs >= -1.0) & (xs <= w)
    y = jnp.clip(ys, 0, h - 1)
    x = jnp.clip(xs, 0, w - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = y - y0
    wx = x - x0
    val = (fmap[:, y0, x0] * ((1 - wy) * (1 - wx))[None]
           + fmap[:, y0, x1] * ((1 - wy) * wx)[None]
           + fmap[:, y1, x0] * (wy * (1 - wx))[None]
           + fmap[:, y1, x1] * (wy * wx)[None])
    return jnp.where(valid[None, :], val, 0.0)


def nearest_zeros(fmap, ys, xs):
    """Nearest-neighbor with zeros outside. [C, H, W] x [P] -> [C, P]."""
    yi = jnp.round(ys).astype(jnp.int32)
    xi = jnp.round(xs).astype(jnp.int32)
    return gather_zeros(fmap, yi, xi)
