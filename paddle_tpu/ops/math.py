"""Math ops (parity: python/paddle/tensor/math.py, ops.py, stat.py).

Every op is a thin Paddle-signature wrapper lowering to jax.numpy through
the tape dispatch (`_dispatch.apply`); XLA fuses chains of these into single
TPU kernels, which replaces Paddle's phi elementwise/reduce CUDA kernel
templates (paddle/phi/kernels/funcs/elementwise_base.h etc.).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, to_tensor
from ..framework import dtype as dtypes
from ._dispatch import apply
from .creation import _coerce


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _scalarize(v):
    """Python scalars stay scalars (weak-typed in jax → no bad promotion)."""
    if isinstance(v, Tensor):
        return v
    if isinstance(v, (int, float, bool, complex, np.number)):
        return v
    return to_tensor(v)


# ---------------------------------------------------------------- unary ----
def _unary(jfn, name):
    def op(x, name=None):
        return apply(jfn, _coerce(x), _name=name)
    op.__name__ = name
    return op


exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
exp2 = _unary(jnp.exp2, "exp2")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
square = _unary(jnp.square, "square")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
abs = _unary(jnp.abs, "abs")
neg = _unary(jnp.negative, "neg")
sign = _unary(jnp.sign, "sign")
sgn = _unary(jnp.sign, "sgn")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
erf = _unary(jax.lax.erf, "erf")
erfinv = _unary(jax.lax.erf_inv, "erfinv")
lgamma = _unary(jax.lax.lgamma, "lgamma")
digamma = _unary(jax.lax.digamma, "digamma")
i0 = _unary(lambda v: jax.lax.bessel_i0e(v) * jnp.exp(jnp.abs(v)), "i0")
i0e = _unary(jax.lax.bessel_i0e, "i0e")
i1e = _unary(jax.lax.bessel_i1e, "i1e")
i1 = _unary(lambda v: jax.lax.bessel_i1e(v) * jnp.exp(jnp.abs(v)), "i1")
conj = _unary(jnp.conj, "conj")
angle = _unary(jnp.angle, "angle")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
frac = _unary(lambda v: v - jnp.trunc(v), "frac")
logit = _unary(lambda v: jnp.log(v / (1 - v)), "logit")


def isnan(x, name=None):
    return apply(jnp.isnan, _coerce(x))


def isinf(x, name=None):
    return apply(jnp.isinf, _coerce(x))


def isfinite(x, name=None):
    return apply(jnp.isfinite, _coerce(x))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf), _coerce(x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), _coerce(x))


# --------------------------------------------------------------- binary ----
def _float_scalar_vs_int_tensor(s, other):
    """paddle/torch scalar rule: a python float (or complex) paired
    with an integer/bool tensor promotes to the DEFAULT dtype —
    float32/complex64 — where under jax_enable_x64 the weak python
    scalar would drag the result to float64/complex128 (r5 fuzz find).
    Inexact tensors keep weak-scalar behavior (f32 + 0.5 stays f32,
    f64 + 0.5 stays f64). Note the isinstance ladder: python floats ARE
    instances of complex, so float is tested first."""
    if (isinstance(other, Tensor)
            and not jnp.issubdtype(other._value.dtype, jnp.inexact)):
        if isinstance(s, float):
            return np.float32(s)
        if isinstance(s, complex):
            return np.complex64(s)
    return s


def _binary(jfn, name):
    def op(x, y, name=None):
        a, b = _scalarize(x), _scalarize(y)
        a, b = (_float_scalar_vs_int_tensor(a, b),
                _float_scalar_vs_int_tensor(b, a))
        return apply(jfn, a, b, _name=name)
    op.__name__ = name
    return op


add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
floor_divide = _binary(jnp.floor_divide, "floor_divide")
remainder = _binary(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
pow = _binary(jnp.power, "pow")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
logaddexp = _binary(jnp.logaddexp, "logaddexp")
hypot = _binary(jnp.hypot, "hypot")
heaviside = _binary(jnp.heaviside, "heaviside")
gcd = _binary(jnp.gcd, "gcd")
lcm = _binary(jnp.lcm, "lcm")
kron = _binary(jnp.kron, "kron")
nextafter = _binary(jnp.nextafter, "nextafter")
copysign = _binary(jnp.copysign, "copysign")
ldexp = _binary(lambda x, y: x * (2.0 ** y).astype(x.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else (x * (2 ** y)), "ldexp")


def divide_no_nan(x, y, name=None):
    a, b = _scalarize(x), _scalarize(y)
    a, b = (_float_scalar_vs_int_tensor(a, b),
            _float_scalar_vs_int_tensor(b, a))
    return apply(lambda a, b: jnp.where(b == 0, 0, a / jnp.where(b == 0, 1, b)),
                 a, b)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    xt = _coerce(x)
    s = _float_scalar_vs_int_tensor(s, xt)
    bias = _float_scalar_vs_int_tensor(bias, xt)
    x = xt
    if bias_after_scale:
        out = apply(lambda v: v * s + bias, _coerce(x))
    else:
        out = apply(lambda v: (v + bias) * s, _coerce(x))
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    ts = [_coerce(t) for t in inputs]
    import functools
    return apply(lambda *vs: functools.reduce(jnp.add, vs), *ts)


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a),
                 _coerce(x), _coerce(y), _scalarize(weight))


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) and min.size == 1 else min
    hi = max.item() if isinstance(max, Tensor) and max.size == 1 else max
    return apply(lambda v: jnp.clip(v, lo, hi), _coerce(x))


def inner(x, y, name=None):
    return apply(jnp.inner, _coerce(x), _coerce(y))


def outer(x, y, name=None):
    return apply(jnp.outer, _coerce(x), _coerce(y))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
                 _coerce(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1,
                                        axis2=axis2), _coerce(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [_coerce(x)]
    has_pre = prepend is not None
    has_app = append is not None
    if has_pre:
        args.append(_coerce(prepend))
    if has_app:
        args.append(_coerce(append))

    def fn(v, *rest):
        pre = rest[0] if has_pre else None
        app = rest[-1] if has_app else None
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)
    return apply(fn, *args)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else (next((i for i, s in enumerate(_coerce(x)._value.shape) if s == 3), -1))
    return apply(lambda a, b: jnp.cross(a, b, axis=ax), _coerce(x), _coerce(y))


def multiplex(inputs, index, name=None):
    ts = [_coerce(t) for t in inputs]
    idx = _coerce(index)
    def fn(ix, *vs):
        stacked = jnp.stack(vs, axis=0)
        rows = jnp.arange(stacked.shape[1])
        return stacked[ix.reshape(-1), rows]
    return apply(fn, idx, *ts)


# ----------------------------------------------------------- reductions ----
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype)
    x = _coerce(x)
    def fn(v):
        out = jnp.sum(v, axis=_axes(axis), keepdims=keepdim, dtype=d)
        return out
    return apply(fn, x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype)
    return apply(lambda v: jnp.nansum(v, axis=_axes(axis), keepdims=keepdim,
                                      dtype=d), _coerce(x))


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.mean(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmean(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    return apply(lambda v: jnp.prod(v, axis=_axes(axis), keepdims=keepdim,
                                    dtype=d), _coerce(x))


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.max(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.min(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jax.scipy.special.logsumexp(
        v, axis=_axes(axis), keepdims=keepdim), _coerce(x))


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.all(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.any(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.count_nonzero(v, axis=_axes(axis),
                                             keepdims=keepdim).astype(jnp.int64),
                 _coerce(x))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.std(v, axis=_axes(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _coerce(x))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.var(v, axis=_axes(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _coerce(x))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(v):
        if mode == "avg":
            return jnp.median(v, axis=_axes(axis), keepdims=keepdim)
        # 'min' mode: lower median
        ax = _axes(axis)
        if ax is None:
            flat = v.reshape(-1)
            k = (flat.shape[0] - 1) // 2
            return jnp.sort(flat)[k]
        srt = jnp.sort(v, axis=ax)
        k = (v.shape[ax] - 1) // 2
        out = jnp.take(srt, k, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out
    return apply(fn, _coerce(x))


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmedian(v, axis=_axes(axis), keepdims=keepdim),
                 _coerce(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q.numpy() if isinstance(q, Tensor) else q
    return apply(lambda v: jnp.quantile(v, jnp.asarray(qv), axis=_axes(axis),
                                        keepdims=keepdim, method=interpolation),
                 _coerce(x))


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    qv = q.numpy() if isinstance(q, Tensor) else q
    return apply(lambda v: jnp.nanquantile(v, jnp.asarray(qv), axis=_axes(axis),
                                           keepdims=keepdim), _coerce(x))


# ------------------------------------------------------------ cumulative ----
def cumsum(x, axis=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=d)
        return jnp.cumsum(v, axis=int(axis), dtype=d)
    return apply(fn, _coerce(x))


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    def fn(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1), dtype=d)
        return jnp.cumprod(v, axis=int(dim), dtype=d)
    return apply(fn, _coerce(x))


def cummax(x, axis=None, dtype="int64", name=None):
    def fn(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=ax)
        n = vv.shape[ax]
        eq = vv == vals
        idx = jnp.arange(n).reshape([-1 if i == (ax % vv.ndim) else 1
                                     for i in range(vv.ndim)])
        idx = jnp.where(eq, idx, -1)
        inds = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
        return vals, inds.astype(dtypes.convert_dtype(dtype))
    return apply(fn, _coerce(x))


def cummin(x, axis=None, dtype="int64", name=None):
    def fn(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.minimum, vv, axis=ax)
        n = vv.shape[ax]
        eq = vv == vals
        idx = jnp.arange(n).reshape([-1 if i == (ax % vv.ndim) else 1
                                     for i in range(vv.ndim)])
        idx = jnp.where(eq, idx, -1)
        inds = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
        return vals, inds.astype(dtypes.convert_dtype(dtype))
    return apply(fn, _coerce(x))


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=ax)
    return apply(fn, _coerce(x))


# ----------------------------------------------------------------- stat ----
def histogram(x, bins=100, min=0, max=0, name=None):
    x = _coerce(x)
    def fn(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h.astype(jnp.int64)
    return apply(fn, x)


def bincount(x, weights=None, minlength=0, name=None):
    x = _coerce(x)
    n = int(np.asarray(x._value).max()) + 1 if x.size else 0
    length = builtins_max(n, int(minlength))
    if weights is None:
        return apply(lambda v: jnp.bincount(v, length=length), x)
    return apply(lambda v, w: jnp.bincount(v, weights=w, length=length),
                 x, _coerce(weights))


def builtins_max(a, b):
    return a if a > b else b


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0),
                 _coerce(x))


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda v: jnp.corrcoef(v, rowvar=rowvar), _coerce(x))


# --------------------------------------------------------------- einsum ----
def einsum(equation, *operands):
    ops_ = [_coerce(o) for o in operands]
    return apply(lambda *vs: jnp.einsum(equation, *vs), *ops_)


# ---------------------------------------------------------------- misc -----
def increment(x, value=1.0, name=None):
    out = apply(lambda v: v + value, _coerce(x))
    x._inplace_update(out)
    return x



def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """Multi-dim histogram (parity: python/paddle/tensor/linalg.py
    histogramdd). x: [N, D]."""
    args = [_coerce(x)]
    if weights is not None:
        args.append(_coerce(weights))

    def fn(v, *rest):
        w = rest[0] if rest else None
        b = bins
        if isinstance(b, (list, tuple)):
            b = [np.asarray(e.numpy()) if hasattr(e, "numpy") else e
                 for e in b]
        r = None
        if ranges is not None:
            rr = np.asarray(ranges, np.float64).reshape(-1, 2)
            r = [tuple(row) for row in rr]
        hist, edges = jnp.histogramdd(v, bins=b, range=r, weights=w,
                                      density=density)
        return (hist,) + tuple(edges)
    out = apply(fn, *args)
    return out[0], list(out[1:])


def inverse(x, name=None):
    """Parity: python/paddle/tensor/math.py inverse (== linalg.inv)."""
    from .linalg import inv
    return inv(x)
