"""GradScaler (parity: python/paddle/amp/grad_scaler.py).

Dynamic loss scaling: scale the loss before backward, unscale grads at
step time, skip the step when any grad is non-finite, and adapt the scale.
On TPU bf16 this is usually a no-op (init with enable=False), but fp16
training and GPU-parity recipes use it unchanged.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from .._grad_mode import no_grad


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        self._dev_state = None  # device-side (scale, good, bad) when a
        #                         compiled step owns the state

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def _sync_from_device(self):
        """Pull compiled-step scaler state to python lazily — per-step
        float() would force a host sync and serialize async dispatch."""
        if self._dev_state is not None:
            s, g, b = self._dev_state
            self._scale = float(s)
            self._good_steps = int(g)
            self._bad_steps = int(b)
            self._dev_state = None

    def get_loss_scaling(self):
        self._sync_from_device()
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        self._sync_from_device()
        return loss * self._scale

    @no_grad()
    def unscale_(self, optimizer):
        if not self._enable:
            return
        self._sync_from_device()
        # one fused program over ALL grads + ONE host sync for the
        # found_inf flag — the old per-param loop dispatched a kernel and
        # forced a device round-trip per parameter (O(#params) syncs)
        with_grads = [p for p in optimizer._parameter_list
                      if p.grad is not None]
        if not with_grads:
            self._found_inf = False
            self._unscaled = True
            return
        gs, found = _eager_unscale(
            [p.grad._value for p in with_grads],
            jnp.asarray(self._scale, jnp.float32))
        for p, g in zip(with_grads, gs):
            p.grad._value = g
        self._found_inf = bool(found)  # the single sync
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        self._sync_from_device()
        self._unscaled = False
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        self._sync_from_device()
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        # the loaded checkpoint supersedes any compiled-step device state
        self._dev_state = None
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


# ---------------------------------------------------------------------------
# Compiled-step integration — the SINGLE implementation of dynamic loss
# scaling inside a jitted train step, shared by jit.bridge.TrainStep and
# fleet.dist_step.DistTrainStep (reference parity: the fused
# update_loss_scaling op, phi/kernels/gpu/amp_kernel.cu).
# ---------------------------------------------------------------------------

def scaler_state_in(scaler):
    """Device tuple (scale f32, good i32, bad i32) fed into the step."""
    if scaler._dev_state is not None:
        return scaler._dev_state
    return (jnp.asarray(scaler._scale, jnp.float32),
            jnp.asarray(scaler._good_steps, jnp.int32),
            jnp.asarray(scaler._bad_steps, jnp.int32))


def scaler_state_out(scaler, st):
    """Store the step's output state WITHOUT a host sync (lazy)."""
    scaler._dev_state = st


import functools as _functools
import jax as _jax


@_jax.jit
def _eager_unscale(grads, scale):
    """Batched eager unscale: same math as compiled_unscale, one
    dispatch for the whole grad list. NOT donated: eager grads often
    wrap numpy-backed buffers (to_tensor), which zero-copy on CPU —
    donating an aliased buffer corrupts the heap."""
    return compiled_unscale(scale, grads)


def compiled_unscale(scale, grads):
    """Unscale grads (f32 math) and compute the any-non-finite flag."""
    import functools as _ft
    inv = (1.0 / scale).astype(jnp.float32)
    grads = [(g.astype(jnp.float32) * inv).astype(g.dtype) for g in grads]
    found_inf = _ft.reduce(
        jnp.logical_or, [jnp.any(~jnp.isfinite(g)) for g in grads])
    return grads, found_inf


def compiled_select_and_adapt(scaler, found_inf, new_p, old_p, new_state,
                              old_state, scaler_st):
    """Skip the whole update on overflow; adapt scale/counters on-device."""
    import jax

    def pick(new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(found_inf, b, a), new, old)

    new_p = pick(new_p, old_p)
    new_state = pick(new_state, old_state)
    scale0, good0, bad0 = scaler_st
    bad = jnp.where(found_inf, bad0 + 1, 0)
    good = jnp.where(found_inf, 0, good0 + 1)
    dec = bad >= scaler._decr_every
    inc = good >= scaler._incr_every
    new_scale = jnp.where(
        dec, jnp.maximum(scale0 * scaler._decr_ratio, 1.0),
        jnp.where(inc, scale0 * scaler._incr_ratio, scale0))
    return new_p, new_state, (new_scale, jnp.where(inc, 0, good),
                              jnp.where(dec, 0, bad))


AmpScaler = GradScaler
