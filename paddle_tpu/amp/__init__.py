"""paddle.amp — automatic mixed precision.

Reference parity: python/paddle/amp/{auto_cast,grad_scaler}.py. O1 works at
the dispatch layer: ops on the white list (matmul/conv/linear/attention —
the MXU ops) run their float inputs in the amp dtype, black-list ops
(softmax/norm/exp/log) stay float32. On TPU the amp dtype defaults to
bfloat16 — no loss scaling is numerically required (bf16 has f32's
exponent range), but GradScaler is kept for API parity and for fp16.
"""
from __future__ import annotations

import contextlib

from ..framework import dtype as dtypes
from .grad_scaler import GradScaler, AmpScaler

_WHITE_LIST = {
    "matmul", "linear", "conv", "flash_attention", "einsum", "bmm", "mm",
    "addmm",
}
_BLACK_LIST = {
    "softmax", "log_softmax", "layer_norm", "rms_norm", "batch_norm",
    "cross_entropy", "exp", "log", "mean", "sum", "cumsum",
}


class _AmpState:
    enabled = False
    dtype = dtypes.bfloat16
    level = "O1"
    custom_white = set()
    custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def amp_dtype_for(op_name):
    """Called by the dispatch layer: returns the target dtype for float
    inputs of `op_name`, or None to leave dtypes alone."""
    if not _state.enabled or not op_name:
        return None
    if op_name in _state.custom_black or op_name in _BLACK_LIST:
        return dtypes.float32
    if _state.level == "O2":
        return _state.dtype
    if op_name in _state.custom_white or op_name in _WHITE_LIST:
        return _state.dtype
    return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast"""
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
            _state.custom_black)
    _state.enabled = bool(enable)
    _state.dtype = dtypes.convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate — O2: cast model params to the amp dtype (norms
    kept f32 per paddle semantics is approximated by casting all floats;
    master weights live in the optimizer accumulators)."""
    d = dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    ms = [models] if single else list(models)
    if level == "O2":
        for m in ms:
            m._to_dtype(d)
    if optimizers is None:
        return models if single else ms
    return (models, optimizers)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class debugging:
    """paddle.amp.debugging namespace (check_numerics, operator stats)."""

    @staticmethod
    def enable_operator_stats_collection():
        from ..ops import _dispatch
        _dispatch._op_stats = {}

    @staticmethod
    def disable_operator_stats_collection():
        from ..ops import _dispatch
        stats = _dispatch._op_stats or {}
        _dispatch._op_stats = None
        if stats:
            print("<------------------- op list -------------------->")
            for (op, dtype), n in sorted(stats.items()):
                print(f"  {op:<32s} {dtype:<12s} calls={n}")
            print("<------------------------------------------------>")
        return stats

    class collect_operator_stats:
        """Context manager parity: paddle.amp.debugging
        .collect_operator_stats."""

        def __enter__(self):
            debugging.enable_operator_stats_collection()
            return self

        def __exit__(self, *exc):
            self.stats = debugging.disable_operator_stats_collection()
            return False

    @staticmethod
    def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
        import jax.numpy as jnp
        import numpy as np
        from ..tensor import Tensor
        t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
        arr = np.asarray(t._value)
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        if n_nan or n_inf:
            raise FloatingPointError(
                f"check_numerics({op_type}/{var_name}): {n_nan} NaN, "
                f"{n_inf} Inf values found")
        return t

    class DebugMode:
        """Parity: paddle.amp.debugging.DebugMode."""
        CHECK_NAN_INF_AND_ABORT = 0
        CHECK_NAN_INF = 1
        CHECK_ALL_FOR_OVERFLOW = 2
        CHECK_ALL = 3
        DUMP_ALL = 4

    class TensorCheckerConfig:
        """Parity: paddle.amp.debugging.TensorCheckerConfig — carries the
        check mode for enable_tensor_checker."""

        def __init__(self, enable=True, debug_mode=None, output_dir=None,
                     checked_op_list=None, skipped_op_list=None,
                     debug_step=None, stack_height_limit=1):
            self.enable = enable
            self.debug_mode = debug_mode
            self.output_dir = output_dir

    @staticmethod
    def enable_tensor_checker(config=None):
        """Every op's concrete inputs are scanned for NaN/Inf (the
        FLAGS_check_nan_inf hook in the dispatcher; jitted programs trap
        via jax_debug_nans)."""
        from ..framework.flags import set_flags
        set_flags({"check_nan_inf": True})

    @staticmethod
    def disable_tensor_checker():
        from ..framework.flags import set_flags
        set_flags({"check_nan_inf": False})

    @staticmethod
    def check_layer_numerics(func):
        """Decorator parity: paddle.amp.debugging.check_layer_numerics —
        scans the wrapped forward's tensor outputs."""
        import functools as _ft

        @_ft.wraps(func)
        def wrapper(*args, **kwargs):
            out = func(*args, **kwargs)
            from ..tensor import Tensor
            outs = out if isinstance(out, (tuple, list)) else [out]
            for o in outs:
                if isinstance(o, Tensor):
                    debugging.check_numerics(
                        o, op_type=getattr(func, "__qualname__", "layer"))
            return out
        return wrapper


def is_autocast_enabled():
    """Parity: paddle.is_autocast_enabled / paddle.amp.is_autocast_enabled."""
    return bool(_state.enabled)


def get_autocast_dtype():
    """Parity: paddle.get_autocast_dtype — the active amp dtype, or
    float32 when autocast is off (matching reference behavior)."""
    from ..framework.dtype import dtype_name
    if not _state.enabled:
        return "float32"
    return dtype_name(_state.dtype)
