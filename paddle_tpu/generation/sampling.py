"""On-device sampling and speculative-verify math — batched operands.

Reference parity: PaddleNLP sampling (paddlenlp/generation/logits_process
TopKProcess/TopPProcess + categorical sampling) and the fused speculative
decoding acceptance rule (Leviathan et al. / PaddleNLP speculate_method),
restructured for TPU serving:

- **Per-request knobs are OPERANDS, not trace constants.** Temperature /
  top-k / top-p / seed enter the compiled decode program as ``[B]``
  vectors, so a batch mixing greedy and sampled tenants — or two tenants
  with different temperatures — runs ONE program and a config change
  never retraces (the retrace-per-config hazard graft-lint GL103 exists
  for). Disabled knobs are in-band: ``temperature <= 0`` means greedy,
  ``top_k <= 0`` and ``top_p >= 1`` mean unfiltered.
- **Counter-based keys.** Every draw derives from
  ``fold_in(key(seed), counter)`` where ``counter`` is the index of the
  token being generated. No key state threads through the loop, so the
  serve loop (whose program order is admission-dependent) and the eager/
  static ``generate`` paths produce the SAME sampled stream for a fixed
  seed — the cross-path parity tests/test_spec_decode.py pins.
- **Greedy is bitwise.** ``temperature <= 0`` rows take
  ``argmax(raw_logits)`` — the exact argmax today's decode program
  computes — selected by ``where``, so a sampling-enabled program serving
  an all-greedy batch emits bit-identical tokens.
- **Speculative verify** (`verify_spans`): given the verify span's
  logits, the drafted tokens, and the per-slot sampling operands, the
  longest accepted draft prefix and the bonus/correction token are
  computed ON DEVICE. Greedy rows accept while ``argmax == draft``
  (lossless: output equals plain greedy decode); sampled rows use the
  rejection-sampling rule specialized to a DETERMINISTIC drafter
  (prompt-lookup proposes one token, i.e. q = δ_draft): accept draft d
  with probability p(d), and on rejection resample from the residual
  norm(max(p − q, 0)) = p with d removed — the emitted stream is then
  distributed exactly as sampling from the target model token by token.

Host-side `propose_ngram_drafts` is the prompt-lookup drafter (cf.
"prompt lookup decoding"): match the request's recent token suffix
against its own prompt+generation history and propose the continuation
of the most recent earlier occurrence — no second model, no device work.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "sampling_operands", "topk_mask",
           "topp_mask", "processed_logits", "sample_tokens",
           "verify_spans", "propose_ngram_drafts"]

_NEG = jnp.float32(-1e30)


class SamplingParams(NamedTuple):
    """Per-request sampling knobs, carried as batched operands.

    ``temperature <= 0`` selects greedy argmax (``top_k``/``top_p`` are
    then irrelevant — argmax is filter-invariant); ``top_k <= 0``
    disables the k filter; ``top_p >= 1`` disables the nucleus filter.
    ``seed`` anchors the request's counter-based key stream: token t of
    the request draws with ``fold_in(key(seed), t)``, so the same
    request replayed through the eager, static, or serve-loop path
    yields the same tokens.
    """
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0


def sampling_operands(params: Sequence[Optional[SamplingParams]]):
    """Stack per-slot SamplingParams (None = greedy) into the operand
    vectors the compiled programs take: dict of np arrays
    ``temperature`` f32, ``top_k`` i32, ``top_p`` f32, ``seed`` i32."""
    n = len(params)
    temp = np.zeros((n,), np.float32)
    topk = np.zeros((n,), np.int32)
    topp = np.ones((n,), np.float32)
    seed = np.zeros((n,), np.int32)
    for i, sp in enumerate(params):
        if sp is None:
            continue
        temp[i] = float(sp.temperature)
        topk[i] = int(sp.top_k)
        topp[i] = float(sp.top_p)
        seed[i] = int(sp.seed)
    return {"temperature": temp, "top_k": topk, "top_p": topp,
            "seed": seed}


# ------------------------------------------------------------- filtering --
def topk_mask(logits, k):
    """Keep each row's top-k logits, mask the rest to -1e30. `k` may be
    a python int or a traced array broadcastable to the row shape;
    ``k <= 0`` (or >= vocab) disables per row — so the filter composes
    into one program for a batch mixing filtered and unfiltered
    requests."""
    v = logits.shape[-1]
    sorted_desc = -jnp.sort(-logits, axis=-1)
    kk = jnp.where(jnp.asarray(k) <= 0, v,
                   jnp.clip(jnp.asarray(k), 1, v)).astype(jnp.int32)
    kk = jnp.broadcast_to(kk, logits.shape[:-1])
    kth = jnp.take_along_axis(sorted_desc, (kk - 1)[..., None], axis=-1)
    return jnp.where(logits < kth, _NEG, logits)


def topp_mask(logits, p):
    """Nucleus filtering with `p` as a (possibly per-row traced)
    operand: keep the smallest prefix of the sorted distribution with
    cumulative probability >= p (the argmax always survives);
    ``p >= 1`` disables per row."""
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    pp = jnp.broadcast_to(jnp.asarray(p, logits.dtype),
                          logits.shape[:-1])[..., None]
    drop = (cum - probs) > pp          # True => outside the nucleus
    kept = jnp.where(drop, jnp.inf, sorted_desc)
    thr = jnp.min(kept, axis=-1, keepdims=True)
    return jnp.where(logits < thr, _NEG, logits)


def processed_logits(logits, temperature, top_k, top_p):
    """The serving logits pipeline (temperature → top-k → top-p) with
    every knob a batched operand. `logits` [..., V] float32; params
    broadcastable to the row shape. Rows with ``temperature <= 0`` are
    scaled by 1 (their sample is replaced by argmax downstream — the
    scale must stay finite, not meaningful).

    One shared descending sort feeds BOTH filters (this runs on every
    sampled decode tick and every verify-span position — two
    independent O(V·log V) sorts would double the kernel's dominant
    cost at real vocab sizes): the post-top-k sorted logits are just
    the sort's first k entries with the tail masked, so the nucleus
    cutoff is computed from the same array, and the two filters
    collapse into one combined per-row threshold. Equivalent to
    ``topp_mask(topk_mask(lg, k), p)`` (pinned by test; exact ties AT
    the k-th logit may shift the nucleus cutoff by a tied duplicate —
    measure-zero for float logits, and the kept set still honors
    ties like the sequential form)."""
    t = jnp.asarray(temperature, jnp.float32)
    safe_t = jnp.where(t <= 0, jnp.float32(1.0),
                       jnp.maximum(t, jnp.float32(1e-6)))
    lg = logits / jnp.broadcast_to(safe_t, logits.shape[:-1])[..., None]
    v = lg.shape[-1]
    sorted_desc = -jnp.sort(-lg, axis=-1)
    kk = jnp.where(jnp.asarray(top_k) <= 0, v,
                   jnp.clip(jnp.asarray(top_k), 1, v)).astype(jnp.int32)
    kk = jnp.broadcast_to(kk, lg.shape[:-1])
    kth = jnp.take_along_axis(sorted_desc, (kk - 1)[..., None], axis=-1)
    rank = jnp.arange(v, dtype=jnp.int32)
    sl = jnp.where(rank < kk[..., None], sorted_desc, _NEG)
    probs = jax.nn.softmax(sl, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    pp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32),
                          lg.shape[:-1])[..., None]
    drop = (cum - probs) > pp
    kept = jnp.where(drop, jnp.inf, sl)
    thr_p = jnp.min(kept, axis=-1, keepdims=True)
    thr = jnp.maximum(thr_p, kth)    # keep iff inside BOTH filters
    return jnp.where(lg < thr, _NEG, lg)


# -------------------------------------------------------------- sampling --
def _row_keys(seed, counter):
    """[N] typed keys: fold_in(key(seed_i), counter_i) — the
    counter-based stream every sampling path shares."""
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.key(s), c)
    )(jnp.asarray(seed, jnp.uint32), jnp.asarray(counter, jnp.uint32))


def sample_tokens(logits, temperature, top_k, top_p, seed, counter):
    """One sampled (or greedy) token per row. logits [B, V] (model
    dtype — argmax runs on the RAW logits so greedy rows are bitwise
    the plain decode argmax); all params [B] operands; `counter` [B] is
    the per-request generated-token index. Returns (tok [B] int32,
    logp [B] float32 — the chosen token's log-probability under the
    distribution it was drawn from: processed for sampled rows, raw
    for greedy rows)."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg32 = logits.astype(jnp.float32)
    proc = processed_logits(lg32, temperature, top_k, top_p)
    keys = _row_keys(seed, counter)
    sampled = jax.vmap(
        lambda l, k: jax.random.categorical(k, l))(proc, keys)
    t = jnp.asarray(temperature, jnp.float32)
    tok = jnp.where(t <= 0, greedy_tok, sampled.astype(jnp.int32))
    base = jnp.where((t <= 0)[:, None], lg32, proc)
    logp = jnp.take_along_axis(jax.nn.log_softmax(base, axis=-1),
                               tok[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    return tok, logp


# ----------------------------------------------------- speculative verify --
def verify_spans(logits, span_ids, q_lens, temperature, top_k, top_p,
                 seed, counter, sampled_mode=True):
    """On-device speculative verification of drafted token spans.

    One verify step ran a span of ``q_lens[b]`` tokens per slot through
    the model: position 0 is the slot's committed last token, positions
    1..q_lens-1 the drafted tokens. ``logits[b, i]`` is the target
    model's next-token distribution AFTER span position i, so position
    i judges draft ``span_ids[b, i+1]``.

    Returns ``(accepted [B] int32, bonus [B] int32)``: `accepted` is
    the longest accepted draft prefix (0..q_lens-1), `bonus` the
    correction/continuation token the target model emits at position
    `accepted` — together the slot commits ``accepted + 1`` new tokens.

    Greedy rows (``temperature <= 0``) accept while the raw argmax
    equals the draft and take the argmax as bonus — the emitted stream
    is exactly plain greedy decode. Sampled rows apply rejection
    sampling against the deterministic drafter (q = δ_draft): accept
    draft d with probability p(d) (u < p(d), u from the position's
    counter-keyed stream); on rejection the bonus is drawn from the
    residual p with d removed (renormalized — norm(max(p − q, 0)));
    when every draft is accepted the bonus is an ordinary sample from
    the final position. Slots with ``q_lens == 1`` carried no drafts:
    accepted = 0 and bonus is exactly a normal decode sample/argmax.

    `counter` [B] is the per-request generated-token index of the
    span's FIRST emitted token; the three per-position draw families
    (accept uniforms, normal samples, residual samples) fold disjoint
    offsets so streams never collide.

    `sampled_mode` is a STATIC (trace-time) switch: a predictor built
    without sampling serves only greedy requests, and the entire
    stochastic half (keys, uniforms, categorical draws, residual
    distributions) compiles away — the greedy verify is argmax-compare
    and nothing else.
    """
    b, qb, v = logits.shape
    t = jnp.asarray(temperature, jnp.float32)
    q_lens = jnp.asarray(q_lens, jnp.int32)
    span_ids = jnp.asarray(span_ids, jnp.int32)

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, Qb]
    if qb > 1:
        drafts = span_ids[:, 1:]                                # [B, Qb-1]
        valid = jnp.arange(1, qb, dtype=jnp.int32)[None, :] \
            < q_lens[:, None]
        g_acc = greedy_tok[:, :-1] == drafts

    sel = lambda a: jnp.take_along_axis(  # noqa: E731
        a, accepted[:, None], axis=1)[:, 0]

    if not sampled_mode:
        if qb > 1:
            lead = jnp.cumprod((g_acc & valid).astype(jnp.int32),
                               axis=-1)
            accepted = jnp.sum(lead, axis=-1).astype(jnp.int32)
        else:
            accepted = jnp.zeros((b,), jnp.int32)
        return accepted, sel(greedy_tok)

    lg32 = logits.astype(jnp.float32)
    proc = processed_logits(
        lg32, t[:, None], jnp.asarray(top_k, jnp.int32)[:, None],
        jnp.asarray(top_p, jnp.float32)[:, None])
    probs = jax.nn.softmax(proc, axis=-1)                       # [B, Qb, V]

    base = _row_keys(seed, counter)                             # [B] keys
    offs = jnp.arange(3 * qb, dtype=jnp.uint32)
    keys = jax.vmap(lambda k: jax.vmap(
        lambda i: jax.random.fold_in(k, i))(offs))(base)  # [B, 3*Qb] keys

    # -- acceptance of drafts (positions 0..qb-2 judge span col 1..) --
    if qb > 1:
        p_draft = jnp.take_along_axis(
            probs[:, :-1], drafts[..., None], axis=-1)[..., 0]
        u = jax.vmap(jax.vmap(jax.random.uniform))(keys[:, :qb - 1])
        s_acc = u < p_draft
        acc = jnp.where((t <= 0)[:, None], g_acc, s_acc) & valid
        lead = jnp.cumprod(acc.astype(jnp.int32), axis=-1)
        accepted = jnp.sum(lead, axis=-1).astype(jnp.int32)
    else:
        accepted = jnp.zeros((b,), jnp.int32)

    # -- bonus token at position `accepted` --
    normal = jax.vmap(jax.vmap(
        lambda k, l: jax.random.categorical(k, l)))(
            keys[:, qb:2 * qb], proc)                           # [B, Qb]
    if qb > 1:
        # residual at position i: p_i with the judged draft removed.
        # log(probs) reintroduces -inf on filtered tokens; positions
        # past the drafts keep a dummy (never selected).
        dr = jnp.concatenate(
            [span_ids[:, 1:], span_ids[:, -1:]], axis=1)        # [B, Qb]
        onehot = jax.nn.one_hot(dr, v, dtype=jnp.bool_)
        res_lg = jnp.where(
            onehot | (probs <= 0), _NEG,
            jnp.log(jnp.maximum(probs, jnp.float32(1e-30))))
        residual = jax.vmap(jax.vmap(
            lambda k, l: jax.random.categorical(k, l)))(
                keys[:, 2 * qb:], res_lg)                       # [B, Qb]
        # degenerate residual (all target mass on the rejected draft —
        # a measure-zero event): fall back to the argmax
        res_dead = jnp.max(res_lg, axis=-1) <= _NEG / 2
        residual = jnp.where(res_dead, greedy_tok, residual)
    else:
        residual = normal

    all_acc = accepted >= q_lens - 1
    s_bonus = jnp.where(all_acc, sel(normal), sel(residual))
    bonus = jnp.where(t <= 0, sel(greedy_tok),
                      s_bonus).astype(jnp.int32)
    return accepted, bonus


# ------------------------------------------------------ prompt-lookup draft --
def propose_ngram_drafts(history: List[int], k: int,
                         ngram_max: int = 3,
                         window: int = 4096) -> List[int]:
    """Prompt-lookup drafting (host-side, no second model): match the
    longest suffix n-gram of `history` (n = ngram_max down to 1)
    against an earlier occurrence in the SAME history (prompt +
    generation) and propose up to `k` tokens that followed the most
    recent match. Returns [] when nothing matches — the tick then runs
    as a plain decode step. `window` bounds the backward scan so a very
    long history costs O(window) per tick, not O(n^2)."""
    n = len(history)
    if k <= 0 or n < 2:
        return []
    lo = max(0, n - window)
    for m in range(min(ngram_max, n - 1), 0, -1):
        pat = history[n - m:]
        for j in range(n - m - 1, lo - 1, -1):
            if history[j:j + m] == pat:
                return list(history[j + m:j + m + k])
    return []
