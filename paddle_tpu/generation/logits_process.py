"""Logits processors for generation — pure-jax, scan-safe.

Reference parity: PaddleNLP paddlenlp/generation/logits_process.py
(LogitsProcessorList, TopKProcess, TopPProcess, RepetitionPenalty,
MinLengthLogitsProcessor). All functions here take/return raw jnp arrays
so they compose inside a jitted decode loop.
"""
from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -1e30


def apply_temperature(logits, temperature):
    t = jnp.maximum(jnp.asarray(temperature, logits.dtype), 1e-6)
    return logits / t


def top_k_filter(logits, k: int):
    """Keep the top-k logits per row, mask the rest. k is static here;
    the math is the shared batched-operand kernel
    (generation.sampling.topk_mask) — the serve loop runs the same
    filter with k as a per-request operand, so eager and serve-loop
    filtering can never drift apart."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    from .sampling import topk_mask
    return topk_mask(logits, k)


def top_p_filter(logits, p):
    """Nucleus filtering: keep the smallest prefix of the sorted
    distribution with cumulative prob >= p (always keeps the argmax).
    Shared batched-operand kernel (generation.sampling.topp_mask) —
    see top_k_filter."""
    from .sampling import topp_mask
    return topp_mask(logits, p)


def repetition_penalty(logits, token_counts, penalty):
    """Divide (positive) / multiply (negative) logits of seen tokens.

    token_counts: [B, V] int — occurrences of each token so far.
    """
    seen = token_counts > 0
    pen = jnp.asarray(penalty, logits.dtype)
    penalized = jnp.where(logits > 0, logits / pen, logits * pen)
    return jnp.where(seen, penalized, logits)


def min_length_mask(logits, cur_len, min_length: int, eos_token_id):
    """Forbid EOS before min_length tokens were generated."""
    if eos_token_id is None or min_length <= 0:
        return logits
    blocked = logits.at[..., eos_token_id].set(_NEG_INF)
    return jnp.where(cur_len < min_length, blocked, logits)
