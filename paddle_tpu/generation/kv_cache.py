"""Static-shape KV cache for XLA-friendly autoregressive decoding.

Reference parity: PaddleNLP generation caches (paddlenlp/transformers/
generation_utils.py `past_key_values`) and the fused block-attention
cache layout of paddle/phi/kernels/fusion/gpu (block_multihead_attention).

TPU-native design: instead of concatenating K/V each step (dynamic shapes
— retrace/recompile every token), the cache is a preallocated
[B, max_len, n_kv_heads, head_dim] buffer per layer written in place with
`lax.dynamic_update_slice` at a traced position. The whole decode loop
then compiles to ONE XLA program (`lax.scan` over steps) with static
shapes, which is the canonical TPU serving pattern.

The serving side lives here too: `PagedKVPool` (refcounted page
allocator over the device-resident paged K/V arrays, with on-device
copy-on-write) and `PrefixCache` (hash-trie over page-aligned prompt
prefixes so repeated system prompts skip prefill — cf. vLLM automatic
prefix caching / SGLang RadixAttention), consumed by
inference.ContinuousBatchingPredictor (docs/SERVING.md).
"""
from __future__ import annotations

from typing import List, NamedTuple


class StaticCacheEntry(NamedTuple):
    """Per-layer cache entry: full K/V buffers plus the write position.

    `k`/`v` are Tensors (or traced arrays) of shape
    [batch, max_len, n_kv_heads, head_dim]; `pos` is a scalar int32
    Tensor — the slot where this step's keys/values are written.
    """
    k: object
    v: object
    pos: object


class StaticKVCache:
    """A list of per-layer StaticCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[StaticCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def static_cache_update(entry: StaticCacheEntry, k, v):
    """Write K/V ([B, s, H, D] Tensors) into the static cache at
    entry.pos (lax.dynamic_update_slice) — THE cache-write contract,
    shared by every model family's attention."""
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import apply

    def upd(cache, new, p):
        z = jnp.int32(0)
        return jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype),
            (z, p.astype(jnp.int32), z, z))

    k_new = apply(upd, entry.k, k, entry.pos, _name="kv_cache_update")
    v_new = apply(upd, entry.v, v, entry.pos, _name="kv_cache_update")
    return k_new, v_new, StaticCacheEntry(k_new, v_new, entry.pos)


class PagedKVPool:
    """Host-side page allocator over the device-resident paged KV arrays
    (reference parity: the block manager of PaddleNLP's serving /
    vLLM's BlockSpaceManager). Pages are shared by all slots; the free
    list and reference counts live on host, the page contents on device.

    Pages are refcounted so prompt prefixes can be shared across
    requests (PrefixCache): `alloc` hands out pages at refcount 1,
    `retain`/`release` adjust the count, and a page returns to the free
    list only when its count reaches zero. `copy_into` implements
    copy-on-write: a request that must append into a shared page first
    copies its contents into an exclusively-owned page on device.

    An optional `reclaimer` (the PrefixCache) is consulted when `alloc`
    runs short: cached-but-unused pages are dropped to satisfy the
    request, and `free_count` reports them as available.
    """

    def __init__(self, n_layers, num_pages, page_size, n_kv_heads,
                 head_dim, dtype="float32", mesh=None):
        import jax.numpy as jnp
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        shape = (num_pages, page_size, n_kv_heads, head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        # tensor-parallel serving: pages shard over the KV-head axis of
        # a 'model' mesh (the paged kernels are head-parallel by
        # construction, so every program variant composes). The host-
        # side bookkeeping — free list, refcounts, page ids — is
        # layout-blind and identical either way; only the device
        # placement of the page arrays changes.
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)
        self.kv_sharding = None
        self.topology = "single"
        if mesh is not None and mesh.shape.get("model", 1) > 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            tp = int(mesh.shape["model"])
            if n_kv_heads % tp:
                raise ValueError(
                    f"cannot shard {n_kv_heads} KV heads over "
                    f"model={tp} (head count must divide)")
            self.kv_sharding = NamedSharding(
                mesh, PartitionSpec(None, None, "model", None))
            self.k = [jax.device_put(a, self.kv_sharding) for a in self.k]
            self.v = [jax.device_put(a, self.kv_sharding) for a in self.v]
            self.topology = f"tp{tp}"
        self._free = list(range(num_pages))
        self._refs = {}
        self.reclaimer = None

    @property
    def free_count(self):
        """Pages obtainable right now: the free list plus cache-held
        pages the reclaimer would drop on demand."""
        extra = (self.reclaimer.reclaimable_count(self)
                 if self.reclaimer is not None else 0)
        return len(self._free) + extra

    def alloc(self, n):
        """n page ids (each at refcount 1), or None if the pool can't
        satisfy the request even after reclaiming cached pages."""
        if n > len(self._free) and self.reclaimer is not None:
            self.reclaimer.reclaim(self, n - len(self._free))
        if n > len(self._free):
            return None
        got, self._free = self._free[:n], self._free[n:]
        for p in got:
            self._refs[p] = 1
        return got

    def retain(self, ids):
        for p in ids:
            self._refs[p] = self._refs.get(p, 0) + 1

    def release(self, ids):
        for p in ids:
            c = self._refs.get(p, 1) - 1
            if c <= 0:
                self._refs.pop(p, None)
                self._free.append(p)
            else:
                self._refs[p] = c

    def ref_count(self, pid):
        return self._refs.get(pid, 0)

    def copy_into(self, src, dst):
        """Device-side page copy (all layers), no host round-trip —
        the write half of copy-on-write. One jitted program updates
        every layer; with buffer donation (non-CPU backends) the cost
        is one page of traffic, not a pool copy per layer."""
        import jax
        import numpy as np
        if not hasattr(self, "_copy_jit"):
            def _copy(kl, vl, s, d):
                return ([k.at[d].set(k[s]) for k in kl],
                        [v.at[d].set(v[s]) for v in vl])
            dn = (0, 1) if jax.default_backend() != "cpu" else ()
            self._copy_jit = jax.jit(_copy, donate_argnums=dn)
        self.k, self.v = self._copy_jit(self.k, self.v,
                                        np.int32(src), np.int32(dst))
        self.k, self.v = list(self.k), list(self.v)

    # ------------------------------------------------ disaggregation --
    def export_span(self, prompt, page_ids, next_token=None):
        """Serialize the pages holding `prompt`'s K/V into a
        transferable :class:`KVPageSpan` (the prefill→decode handoff of
        docs/SERVING.md "Disaggregated prefill/decode"). `page_ids` is
        the request's own block-table prefix — ``ceil(len(prompt)/page)``
        entries; `next_token` is the greedy first token the prefill side
        resolved, carried so the decode side can resume without a
        suffix prefill.

        Transport is serialized host memory for now; the span payload
        is plain per-layer numpy, so an ICI/DMA device-to-device path
        can replace the gather/scatter endpoints without changing the
        interface. TP head-sharded pools export the UNSHARDED view (the
        host gather assembles shards); the import side reshards to its
        own layout and records a fallback when layouts differ.
        """
        import numpy as np
        page = self.page_size
        n = len(prompt)
        n_full = n // page
        partial_len = n % page
        want = n_full + (1 if partial_len else 0)
        if want == 0 or len(page_ids) < want:
            raise ValueError(
                f"export_span: need {want} pages for a {n}-token prompt, "
                f"got {len(page_ids)} page ids")
        sel = np.asarray(list(page_ids[:want]), dtype=np.int32)  # graft-lint: ok[GL102] host-side page-id list, no device transfer
        # host gather: np.array on a (possibly sharded) device array
        # fetches and assembles shards — the designed sync point of the
        # serialized-host transport.
        k_pages = [np.array(k[sel]) for k in self.k]   # graft-lint: ok[GL102] designed host-transfer gather of the KV handoff span
        v_pages = [np.array(v[sel]) for v in self.v]   # graft-lint: ok[GL102] designed host-transfer gather of the KV handoff span
        if partial_len:
            # zero the stale tail of the trailing partial page so the
            # checksum (and bitwise round-trip equality) is a function
            # of the prompt's K/V only, not of prior page tenants
            for a in k_pages:
                a[-1, partial_len:] = 0
            for a in v_pages:
                a[-1, partial_len:] = 0
        return KVPageSpan(
            prompt=tuple(int(t) for t in prompt),
            next_token=(None if next_token is None else int(next_token)),
            page_size=page, n_kv_heads=self.n_kv_heads,
            head_dim=self.head_dim, dtype=self.dtype,
            topology=self.topology, k_pages=k_pages, v_pages=v_pages)

    def import_span(self, span, prefix_cache=None):
        """Materialize a :class:`KVPageSpan` into this pool, deduping
        against pages already resident in `prefix_cache` (only missing
        pages are allocated and scattered). Returns a stats dict:
        ``page_ids`` (full table prefix covering the span's prompt, in
        order), ``imported``/``reused`` page counts, ``bytes`` actually
        transferred, and ``resharded`` (True when the span came from a
        different KV layout and was laid out anew on import — also
        recorded via kernels fallback telemetry).

        Raises ``ValueError`` on checksum mismatch (corrupted span) or
        geometry disagreement. When `prefix_cache` is given the
        imported pages are inserted into the trie (which then holds
        their references — the serve loop's full-prefix-hit path picks
        them up); without one the caller owns the returned refs.
        """
        import numpy as np
        if not span.verify():
            raise ValueError("KVPageSpan checksum mismatch (corrupted "
                             "or torn handoff payload)")
        if (span.page_size != self.page_size
                or span.n_kv_heads != self.n_kv_heads
                or span.head_dim != self.head_dim
                or span.dtype != self.dtype
                or len(span.k_pages) != len(self.k)):
            raise ValueError(
                "KVPageSpan geometry mismatch: span "
                f"(page={span.page_size}, heads={span.n_kv_heads}, "
                f"dim={span.head_dim}, dtype={span.dtype}, "
                f"layers={len(span.k_pages)}) vs pool "
                f"(page={self.page_size}, heads={self.n_kv_heads}, "
                f"dim={self.head_dim}, dtype={self.dtype}, "
                f"layers={len(self.k)})")
        resharded = span.topology != self.topology
        if resharded:
            # cross-layout handoff: the span was gathered from another
            # sharding; scattering below lays it out for THIS pool.
            # Recorded as a fallback so autotune/reports can see
            # reshard traffic on the handoff path.
            from ..kernels._common import note_fallback
            note_fallback("kv_span_import", "reshard")
        page = self.page_size
        prompt = span.prompt
        n = len(prompt)
        n_full = n // page
        partial_len = n % page
        total = n_full + (1 if partial_len else 0)
        reused = []
        if prefix_cache is not None:
            pages, covered, partial, _nt = prefix_cache.lookup(prompt)
            reused = list(pages)
            if covered == n or (partial is not None
                                and covered + partial[1] == n):
                # fully resident: nothing to transfer
                return {"page_ids": reused + (
                            [partial[0]] if partial is not None else []),
                        "imported": 0, "reused": total, "bytes": 0,
                        "resharded": resharded}
        missing = list(range(len(reused), total))
        ids = self.alloc(len(missing))
        if ids is None:
            raise MemoryError(
                f"import_span: pool cannot hold {len(missing)} pages "
                f"(free={self.free_count})")
        sel = np.asarray(missing, dtype=np.int32)  # graft-lint: ok[GL102] host-side page-index list, no device transfer
        dst = np.asarray(ids, dtype=np.int32)      # graft-lint: ok[GL102] host-side page-index list, no device transfer
        nbytes = 0
        import jax
        import jax.numpy as jnp
        for layer in range(len(self.k)):
            upd_k = np.ascontiguousarray(span.k_pages[layer][sel])
            upd_v = np.ascontiguousarray(span.v_pages[layer][sel])
            nbytes += upd_k.nbytes + upd_v.nbytes
            jk, jv = jnp.asarray(upd_k), jnp.asarray(upd_v)
            if self.kv_sharding is not None:
                # reshard-on-import: lay the replicated host pages out
                # on this pool's head-sharded mesh before the scatter
                from jax.sharding import NamedSharding, PartitionSpec
                upd_sh = NamedSharding(self.kv_sharding.mesh,
                                       PartitionSpec(None, None,
                                                     "model", None))
                jk = jax.device_put(jk, upd_sh)
                jv = jax.device_put(jv, upd_sh)
            self.k[layer] = self.k[layer].at[dst].set(
                jk.astype(self.k[layer].dtype))
            self.v[layer] = self.v[layer].at[dst].set(
                jv.astype(self.v[layer].dtype))
        all_ids = reused + ids
        if prefix_cache is not None:
            next_tokens = None
            if span.next_token is not None:
                next_tokens = [None] * (n - 1) + [span.next_token]
            prefix_cache.insert(prompt, all_ids, next_tokens, self)
            # the trie holds the surviving references; drop the alloc
            # refs so imported pages are reclaimable like any cached
            # prefix once unused
            self.release(ids)
        return {"page_ids": all_ids, "imported": len(ids),
                "reused": len(reused), "bytes": nbytes,
                "resharded": resharded}


class KVPageSpan:
    """One request's prefilled KV pages, serialized for transfer between
    replicas (prefill→decode handoff). Pages are keyed by the same
    content hashes as the PrefixCache trie (`prefix_page_keys`), so the
    import side dedups against already-resident prefixes instead of
    re-transferring them.

    The payload is per-layer numpy — `k_pages[l]`/`v_pages[l]` are
    [n_pages, page_size, n_kv_heads, head_dim] host arrays covering the
    prompt (trailing partial page zero-padded past its valid tokens).
    `checksum` is a SHA-256 over header + payload, verified on import
    (a corrupted span is rejected, never half-materialized).

    `trace` is an optional plain-dict TraceContext
    (observability.tracing.TraceContext.to_dict) stamped by the router
    at handoff so the decode side's spans join the request's trace.
    Like `topology`, it is transport metadata — NOT part of the
    checksum (the same KV payload re-handed with a different trace
    must still verify).
    """

    __slots__ = ("prompt", "next_token", "page_size", "n_kv_heads",
                 "head_dim", "dtype", "topology", "k_pages", "v_pages",
                 "checksum", "trace")

    def __init__(self, prompt, next_token, page_size, n_kv_heads,
                 head_dim, dtype, topology, k_pages, v_pages,
                 checksum=None, trace=None):
        self.prompt = tuple(prompt)
        self.next_token = next_token
        self.page_size = int(page_size)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)
        self.topology = str(topology)
        self.k_pages = list(k_pages)
        self.v_pages = list(v_pages)
        self.trace = dict(trace) if trace else None
        self.checksum = (checksum if checksum is not None
                         else self.compute_checksum())

    @property
    def n_pages(self) -> int:
        return int(self.k_pages[0].shape[0]) if self.k_pages else 0

    @property
    def nbytes(self) -> int:
        return (sum(a.nbytes for a in self.k_pages)
                + sum(a.nbytes for a in self.v_pages))

    @property
    def keys(self):
        """The trie keys of the span's FULL pages (the dedup join key)."""
        return prefix_page_keys(self.prompt, self.page_size)

    def compute_checksum(self) -> str:
        import hashlib
        import numpy as np
        h = hashlib.sha256()
        h.update(repr((self.prompt, self.next_token, self.page_size,
                       self.n_kv_heads, self.head_dim,
                       self.dtype)).encode())
        for a in self.k_pages:
            h.update(np.ascontiguousarray(a).tobytes())
        for a in self.v_pages:
            h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()

    def verify(self) -> bool:
        return self.checksum == self.compute_checksum()


def prefix_page_keys(prompt, page_size):
    """The page-aligned prefix keys of `prompt`: one hashable key per
    FULL KV page (``ceil`` is wrong here — a trailing sub-page chunk is
    a *partial*, not a page key). This is THE shared key function:
    PrefixCache trie edges use exactly these keys, and the serving
    router (serving/router.py) hashes prompts the same way to route a
    session to the replica already holding its cached pages — the two
    must never diverge, or affinity routing would chase pages that the
    cache will not recognize."""
    page = int(page_size)
    return tuple(tuple(prompt[m:m + page])
                 for m in range(0, len(prompt) - page + 1, page))


class _PrefixNode:
    __slots__ = ("page", "next_token", "last_use", "children", "partials")

    def __init__(self, page=None, next_token=None, last_use=0):
        self.page = page
        self.next_token = next_token
        self.last_use = last_use
        self.children = {}   # full page-size token tuple -> _PrefixNode
        self.partials = {}   # sub-page token tuple -> [page, next_token, use]


class PrefixCache:
    """Hash-trie over page-aligned prompt prefixes (cf. vLLM automatic
    prefix caching / SGLang RadixAttention): each trie edge is one KV
    page worth of token ids, each node holds the physical page that
    caches that prefix's K/V plus the greedy next token after it.

    A node additionally stores *partial* trailing chunks (< page_size
    tokens) so prompts that are not page-multiples still share their
    final page; a request extending a partial chunk copies the page
    first (copy-on-write at the divergence page — the pool refcount
    stays intact for the cached reader).

    The trie retains one pool reference per cached page; pages whose
    only reference is the trie are reclaimable on allocation pressure
    (LRU leaf-first) and are reported as free by the pool.
    """

    def __init__(self, page_size):
        self.page = int(page_size)
        self._root = _PrefixNode()
        self._clock = 0

    def _bump(self):
        self._clock += 1
        return self._clock

    # ------------------------------------------------------------- read --
    def lookup(self, prompt):
        """Longest cached page-aligned prefix of `prompt`.

        Returns (pages, covered, partial, next_token): `pages` are the
        full shared page ids covering `covered - (partial and its len)`
        ... specifically full pages cover the first len(pages)*page
        tokens; `partial`, when not None, is (page_id, n_tokens) for a
        shared sub-page chunk extending the covered span (the caller
        must copy-on-write that page before appending); `next_token` is
        the cached greedy continuation when the WHOLE prompt is covered
        (else None)."""
        node = self._root
        pages = []
        m = 0
        n = len(prompt)
        for key in prefix_page_keys(prompt, self.page):
            child = node.children.get(key)
            if child is None:
                break
            child.last_use = self._bump()
            pages.append(child.page)
            m += self.page
            node = child
        next_token = node.next_token if (m == n and m > 0) else None
        partial = None
        if m < n:
            rem = tuple(prompt[m:])
            best = None
            for toks, rec in node.partials.items():
                if (len(toks) <= len(rem) and rem[:len(toks)] == toks
                        and (best is None or len(toks) > len(best[0]))):
                    best = (toks, rec)
            if best is not None:
                toks, rec = best
                rec[2] = self._bump()
                partial = (rec[0], len(toks))
                if m + len(toks) == n and rec[1] is not None:
                    next_token = rec[1]
        return pages, m, partial, next_token

    # ------------------------------------------------------------ write --
    def insert(self, prompt, page_ids, next_tokens, pool):
        """Record a freshly prefilled prompt. `page_ids`: the pages
        holding the prompt's K/V in order (ceil(len/page) entries, the
        request's own table prefix). `next_tokens[i]` is the greedy
        token after prompt position i (None where unknown, e.g. the
        already-cached prefix of a suffix prefill). Existing nodes are
        left untouched; new nodes retain their page in the pool."""
        node = self._root
        m, i, n = 0, 0, len(prompt)
        for chunk in prefix_page_keys(prompt, self.page):
            child = node.children.get(chunk)
            if child is None:
                nt = next_tokens[m + self.page - 1] if next_tokens else None
                child = _PrefixNode(page_ids[i], nt, self._bump())
                pool.retain([page_ids[i]])
                node.children[chunk] = child
            m += self.page
            i += 1
            node = child
        if m < n:
            rem = tuple(prompt[m:])
            if rem not in node.partials:
                nt = next_tokens[n - 1] if next_tokens else None
                node.partials[rem] = [page_ids[i], nt, self._bump()]
                pool.retain([page_ids[i]])

    # ---------------------------------------------------------- reclaim --
    def _droppable(self, pool):
        """Yield (last_use, kind, node, key) for every entry whose page
        the pool would actually free (trie holds the only reference)."""
        out = []

        def walk(node):
            for toks, rec in node.partials.items():
                if pool.ref_count(rec[0]) == 1:
                    out.append((rec[2], "partial", node, toks))
            for chunk, child in node.children.items():
                if (not child.children and not child.partials
                        and pool.ref_count(child.page) == 1):
                    out.append((child.last_use, "leaf", node, chunk))
                else:
                    walk(child)

        walk(self._root)
        return out

    def reclaimable_count(self, pool):
        """Pages the trie holds that no request is using (one linear
        walk). Slightly optimistic: a ref-1 interior node above a
        pinned descendant counts here but cannot actually be freed
        until the descendant's user evicts — `alloc` handles that by
        re-checking after `reclaim`, and once the pool is idle the
        count is exact (the leak-accounting case)."""
        count = 0

        def walk(node):
            nonlocal count
            for rec in node.partials.values():
                if pool.ref_count(rec[0]) == 1:
                    count += 1
            for child in node.children.values():
                if pool.ref_count(child.page) == 1:
                    count += 1
                walk(child)

        walk(self._root)
        return count

    def reclaim(self, pool, need):
        """Drop least-recently-used unpinned leaves until `need` pages
        were freed (or nothing droppable remains). Returns pages freed."""
        freed = 0
        while freed < need:
            cands = self._droppable(pool)
            if not cands:
                break
            cands.sort(key=lambda c: c[0])
            take = cands[:max(need - freed, 1)]
            for _, kind, parent, key in take:
                if kind == "partial":
                    rec = parent.partials.pop(key)
                    pool.release([rec[0]])
                else:
                    child = parent.children.pop(key)
                    pool.release([child.page])
                freed += 1
                if freed >= need:
                    break
        if freed:
            # page-eviction telemetry: cached-but-idle pages dropped
            # under allocation pressure. A sustained rate means the
            # pool is undersized for the working set — the signal
            # tools/autotune.py turns into a num_pages proposal.
            from ..observability import metrics as _obsm
            _obsm.counter("serving.page_evictions").inc(freed)
        return freed

    def clear(self, pool):
        """Release every cached page (used by tests and pool teardown)."""

        def walk(node):
            for rec in node.partials.values():
                pool.release([rec[0]])
            for child in node.children.values():
                walk(child)
                pool.release([child.page])

        walk(self._root)
        self._root = _PrefixNode()


class PagedCacheEntry(NamedTuple):
    """Per-layer paged KV cache (reference parity: the block KV layout of
    paddle/phi/kernels/fusion/gpu block_multihead_attention / vLLM).

    `k_pages`/`v_pages`: [num_pages, page_size, n_kv_heads, head_dim];
    `block_table`: [B, pages_per_seq] int32 page ids per slot;
    `context_lens`: [B] int32 tokens already cached per slot (BEFORE the
    token being decoded). `ragged_meta` (optional): host-built metadata
    from kernels.paged_attention.build_ragged_meta for the POST-write
    lengths (context_lens + 1) — when present, attention runs the
    ragged-grid kernel (only valid (seq, page) pairs enter the grid).

    `q_lens` (optional, [B] int32): per-slot QUERY SPAN lengths for the
    MIXED prefill+decode step — slot b's forward carries q_lens[b]
    tokens (a prefill chunk, or 1 for a decode tick) starting at
    absolute position context_lens[b]. When set, attention dispatches
    to `paged_cache_mixed_update_attend` (span K/V scatter + the
    variable-query ragged kernel) and `ragged_meta`, if present, must
    be built for the post-write lengths context_lens + q_lens.
    """
    k_pages: object
    v_pages: object
    block_table: object
    context_lens: object
    ragged_meta: object = None
    q_lens: object = None


class PagedKVCache:
    """A list of per-layer PagedCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[PagedCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def paged_cache_update_attend(entry: PagedCacheEntry, q, k, v, scale=None):
    """Decode-step contract for the paged cache: write this step's K/V
    (one token per slot) into each slot's current page position, then
    attend the query token against the slot's pages with the paged
    Pallas kernel. q: [B, 1, H, D]; k/v: [B, 1, Hkv, D] → (out
    [B, 1, H, D], updated entry). Gradients are not defined (serving
    path)."""
    import jax.numpy as jnp
    from ..ops._dispatch import apply
    from ..kernels.paged_attention import (paged_attention,
                                           paged_attention_ragged)

    if entry.q_lens is not None:
        # mixed prefill+decode step: variable-length query spans
        return paged_cache_mixed_update_attend(entry, q, k, v, scale)

    meta = entry.ragged_meta

    def fn(kp, vp, bt, cl, qv, kv, vv, *meta_arrs):
        bsz = qv.shape[0]
        page = kp.shape[1]
        rows = jnp.arange(bsz)
        pidx = bt[rows, (cl // page).astype(jnp.int32)]
        off = (cl % page).astype(jnp.int32)
        kp2 = kp.at[pidx, off].set(kv[:, 0].astype(kp.dtype))
        vp2 = vp.at[pidx, off].set(vv[:, 0].astype(vp.dtype))
        if meta_arrs:
            mk = dict(zip(("seq", "page", "ordinal", "first", "last",
                           "valid"), meta_arrs))
            out = paged_attention_ragged(qv[:, 0], kp2, vp2, cl + 1, mk,
                                         scale)
        else:
            out = paged_attention(qv[:, 0], kp2, vp2, bt, cl + 1, scale)
        return out[:, None].astype(qv.dtype), kp2, vp2

    extra = () if meta is None else tuple(
        meta[k] for k in ("seq", "page", "ordinal", "first", "last",
                          "valid"))
    out, kp2, vp2 = apply(fn, entry.k_pages, entry.v_pages,
                          entry.block_table, entry.context_lens, q, k, v,
                          *extra, _name="paged_attention_decode")
    new_entry = PagedCacheEntry(kp2, vp2, entry.block_table,
                                entry.context_lens, entry.ragged_meta)
    return out, new_entry


def paged_cache_mixed_update_attend(entry: PagedCacheEntry, q, k, v,
                                    scale=None):
    """MIXED-step contract for the paged cache: each slot carries a
    query span of entry.q_lens[b] tokens (a prefill chunk, or 1 for a
    decode tick) starting at absolute position entry.context_lens[b].
    The span's K/V is scattered into the slot's pages IN-GRAPH, then
    the span attends causally over the pages with the variable-query
    ragged kernel (kernels.paged_attention.paged_attention_ragged_varq)
    — one compiled step serves a batch mixing mid-prefill and
    mid-decode requests. q: [B, Qb, H, D]; k/v: [B, Qb, Hkv, D] →
    (out [B, Qb, H, D], updated entry). Padding span positions (i >=
    q_lens[b]) write nothing (the scatter keeps the old page contents)
    and read back zeros. Gradients are not defined (serving path)."""
    import jax.numpy as jnp
    from ..ops._dispatch import apply
    from ..kernels.paged_attention import (paged_attention_varq,
                                           paged_attention_ragged_varq)

    meta = entry.ragged_meta

    def fn(kp, vp, bt, cl, ql, qv, kv, vv, *meta_arrs):
        qb = qv.shape[1]
        page = kp.shape[1]
        i = jnp.arange(qb, dtype=jnp.int32)[None, :]
        pos = cl[:, None].astype(jnp.int32) + i            # [B, Qb]
        writing = i < ql[:, None].astype(jnp.int32)        # [B, Qb]
        pslot = jnp.clip(pos // page, 0, bt.shape[1] - 1)
        # padding span positions write NOTHING: their destination page
        # is forced out of bounds and the scatter drops them. (Writing
        # their own gathered contents back instead would race: a
        # padding position past the END of a fully-allocated table
        # clips into the slot's last real page, and duplicate scatter
        # indices carrying different values — stale gather vs this
        # step's real K/V — have an unspecified winner.)
        dst_page = jnp.where(writing,
                             jnp.take_along_axis(bt, pslot, axis=1),
                             jnp.int32(kp.shape[0]))       # [B, Qb]
        dst_off = (pos % page).astype(jnp.int32)
        kp2 = kp.at[dst_page, dst_off].set(kv.astype(kp.dtype),
                                           mode="drop")
        vp2 = vp.at[dst_page, dst_off].set(vv.astype(vp.dtype),
                                           mode="drop")
        kv_lens = cl.astype(jnp.int32) + ql.astype(jnp.int32)
        if meta_arrs:
            mk = dict(zip(("seq", "page", "ordinal", "first", "last",
                           "valid"), meta_arrs))
            out = paged_attention_ragged_varq(qv, kp2, vp2, kv_lens, ql,
                                              mk, scale, block_tables=bt)
        else:
            out = paged_attention_varq(qv, kp2, vp2, bt, kv_lens, ql,
                                       scale)
        return out.astype(qv.dtype), kp2, vp2

    extra = () if meta is None else tuple(
        meta[k] for k in ("seq", "page", "ordinal", "first", "last",
                          "valid"))
    out, kp2, vp2 = apply(fn, entry.k_pages, entry.v_pages,
                          entry.block_table, entry.context_lens,
                          entry.q_lens, q, k, v, *extra,
                          _name="paged_attention_mixed")
    new_entry = PagedCacheEntry(kp2, vp2, entry.block_table,
                                entry.context_lens, entry.ragged_meta,
                                entry.q_lens)
    return out, new_entry
