"""Static-shape KV cache for XLA-friendly autoregressive decoding.

Reference parity: PaddleNLP generation caches (paddlenlp/transformers/
generation_utils.py `past_key_values`) and the fused block-attention
cache layout of paddle/phi/kernels/fusion/gpu (block_multihead_attention).

TPU-native design: instead of concatenating K/V each step (dynamic shapes
— retrace/recompile every token), the cache is a preallocated
[B, max_len, n_kv_heads, head_dim] buffer per layer written in place with
`lax.dynamic_update_slice` at a traced position. The whole decode loop
then compiles to ONE XLA program (`lax.scan` over steps) with static
shapes, which is the canonical TPU serving pattern.
"""
from __future__ import annotations

from typing import List, NamedTuple


class StaticCacheEntry(NamedTuple):
    """Per-layer cache entry: full K/V buffers plus the write position.

    `k`/`v` are Tensors (or traced arrays) of shape
    [batch, max_len, n_kv_heads, head_dim]; `pos` is a scalar int32
    Tensor — the slot where this step's keys/values are written.
    """
    k: object
    v: object
    pos: object


class StaticKVCache:
    """A list of per-layer StaticCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[StaticCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def static_cache_update(entry: StaticCacheEntry, k, v):
    """Write K/V ([B, s, H, D] Tensors) into the static cache at
    entry.pos (lax.dynamic_update_slice) — THE cache-write contract,
    shared by every model family's attention."""
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import apply

    def upd(cache, new, p):
        z = jnp.int32(0)
        return jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype),
            (z, p.astype(jnp.int32), z, z))

    k_new = apply(upd, entry.k, k, entry.pos, _name="kv_cache_update")
    v_new = apply(upd, entry.v, v, entry.pos, _name="kv_cache_update")
    return k_new, v_new, StaticCacheEntry(k_new, v_new, entry.pos)
