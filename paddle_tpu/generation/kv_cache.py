"""Static-shape KV cache for XLA-friendly autoregressive decoding.

Reference parity: PaddleNLP generation caches (paddlenlp/transformers/
generation_utils.py `past_key_values`) and the fused block-attention
cache layout of paddle/phi/kernels/fusion/gpu (block_multihead_attention).

TPU-native design: instead of concatenating K/V each step (dynamic shapes
— retrace/recompile every token), the cache is a preallocated
[B, max_len, n_kv_heads, head_dim] buffer per layer written in place with
`lax.dynamic_update_slice` at a traced position. The whole decode loop
then compiles to ONE XLA program (`lax.scan` over steps) with static
shapes, which is the canonical TPU serving pattern.

The serving side lives here too: `PagedKVPool` (refcounted page
allocator over the device-resident paged K/V arrays, with on-device
copy-on-write) and `PrefixCache` (hash-trie over page-aligned prompt
prefixes so repeated system prompts skip prefill — cf. vLLM automatic
prefix caching / SGLang RadixAttention), consumed by
inference.ContinuousBatchingPredictor (docs/SERVING.md).
"""
from __future__ import annotations

from typing import List, NamedTuple


class StaticCacheEntry(NamedTuple):
    """Per-layer cache entry: full K/V buffers plus the write position.

    `k`/`v` are Tensors (or traced arrays) of shape
    [batch, max_len, n_kv_heads, head_dim]; `pos` is a scalar int32
    Tensor — the slot where this step's keys/values are written.
    """
    k: object
    v: object
    pos: object


class StaticKVCache:
    """A list of per-layer StaticCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[StaticCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def static_cache_update(entry: StaticCacheEntry, k, v):
    """Write K/V ([B, s, H, D] Tensors) into the static cache at
    entry.pos (lax.dynamic_update_slice) — THE cache-write contract,
    shared by every model family's attention."""
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import apply

    def upd(cache, new, p):
        z = jnp.int32(0)
        return jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype),
            (z, p.astype(jnp.int32), z, z))

    k_new = apply(upd, entry.k, k, entry.pos, _name="kv_cache_update")
    v_new = apply(upd, entry.v, v, entry.pos, _name="kv_cache_update")
    return k_new, v_new, StaticCacheEntry(k_new, v_new, entry.pos)


class PagedKVPool:
    """Host-side page allocator over the device-resident paged KV arrays
    (reference parity: the block manager of PaddleNLP's serving /
    vLLM's BlockSpaceManager). Pages are shared by all slots; the free
    list and reference counts live on host, the page contents on device.

    Pages are refcounted so prompt prefixes can be shared across
    requests (PrefixCache): `alloc` hands out pages at refcount 1,
    `retain`/`release` adjust the count, and a page returns to the free
    list only when its count reaches zero. `copy_into` implements
    copy-on-write: a request that must append into a shared page first
    copies its contents into an exclusively-owned page on device.

    An optional `reclaimer` (the PrefixCache) is consulted when `alloc`
    runs short: cached-but-unused pages are dropped to satisfy the
    request, and `free_count` reports them as available.
    """

    def __init__(self, n_layers, num_pages, page_size, n_kv_heads,
                 head_dim, dtype="float32", mesh=None):
        import jax.numpy as jnp
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        shape = (num_pages, page_size, n_kv_heads, head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        # tensor-parallel serving: pages shard over the KV-head axis of
        # a 'model' mesh (the paged kernels are head-parallel by
        # construction, so every program variant composes). The host-
        # side bookkeeping — free list, refcounts, page ids — is
        # layout-blind and identical either way; only the device
        # placement of the page arrays changes.
        self.kv_sharding = None
        if mesh is not None and mesh.shape.get("model", 1) > 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            tp = int(mesh.shape["model"])
            if n_kv_heads % tp:
                raise ValueError(
                    f"cannot shard {n_kv_heads} KV heads over "
                    f"model={tp} (head count must divide)")
            self.kv_sharding = NamedSharding(
                mesh, PartitionSpec(None, None, "model", None))
            self.k = [jax.device_put(a, self.kv_sharding) for a in self.k]
            self.v = [jax.device_put(a, self.kv_sharding) for a in self.v]
        self._free = list(range(num_pages))
        self._refs = {}
        self.reclaimer = None

    @property
    def free_count(self):
        """Pages obtainable right now: the free list plus cache-held
        pages the reclaimer would drop on demand."""
        extra = (self.reclaimer.reclaimable_count(self)
                 if self.reclaimer is not None else 0)
        return len(self._free) + extra

    def alloc(self, n):
        """n page ids (each at refcount 1), or None if the pool can't
        satisfy the request even after reclaiming cached pages."""
        if n > len(self._free) and self.reclaimer is not None:
            self.reclaimer.reclaim(self, n - len(self._free))
        if n > len(self._free):
            return None
        got, self._free = self._free[:n], self._free[n:]
        for p in got:
            self._refs[p] = 1
        return got

    def retain(self, ids):
        for p in ids:
            self._refs[p] = self._refs.get(p, 0) + 1

    def release(self, ids):
        for p in ids:
            c = self._refs.get(p, 1) - 1
            if c <= 0:
                self._refs.pop(p, None)
                self._free.append(p)
            else:
                self._refs[p] = c

    def ref_count(self, pid):
        return self._refs.get(pid, 0)

    def copy_into(self, src, dst):
        """Device-side page copy (all layers), no host round-trip —
        the write half of copy-on-write. One jitted program updates
        every layer; with buffer donation (non-CPU backends) the cost
        is one page of traffic, not a pool copy per layer."""
        import jax
        import numpy as np
        if not hasattr(self, "_copy_jit"):
            def _copy(kl, vl, s, d):
                return ([k.at[d].set(k[s]) for k in kl],
                        [v.at[d].set(v[s]) for v in vl])
            dn = (0, 1) if jax.default_backend() != "cpu" else ()
            self._copy_jit = jax.jit(_copy, donate_argnums=dn)
        self.k, self.v = self._copy_jit(self.k, self.v,
                                        np.int32(src), np.int32(dst))
        self.k, self.v = list(self.k), list(self.v)


def prefix_page_keys(prompt, page_size):
    """The page-aligned prefix keys of `prompt`: one hashable key per
    FULL KV page (``ceil`` is wrong here — a trailing sub-page chunk is
    a *partial*, not a page key). This is THE shared key function:
    PrefixCache trie edges use exactly these keys, and the serving
    router (serving/router.py) hashes prompts the same way to route a
    session to the replica already holding its cached pages — the two
    must never diverge, or affinity routing would chase pages that the
    cache will not recognize."""
    page = int(page_size)
    return tuple(tuple(prompt[m:m + page])
                 for m in range(0, len(prompt) - page + 1, page))


class _PrefixNode:
    __slots__ = ("page", "next_token", "last_use", "children", "partials")

    def __init__(self, page=None, next_token=None, last_use=0):
        self.page = page
        self.next_token = next_token
        self.last_use = last_use
        self.children = {}   # full page-size token tuple -> _PrefixNode
        self.partials = {}   # sub-page token tuple -> [page, next_token, use]


class PrefixCache:
    """Hash-trie over page-aligned prompt prefixes (cf. vLLM automatic
    prefix caching / SGLang RadixAttention): each trie edge is one KV
    page worth of token ids, each node holds the physical page that
    caches that prefix's K/V plus the greedy next token after it.

    A node additionally stores *partial* trailing chunks (< page_size
    tokens) so prompts that are not page-multiples still share their
    final page; a request extending a partial chunk copies the page
    first (copy-on-write at the divergence page — the pool refcount
    stays intact for the cached reader).

    The trie retains one pool reference per cached page; pages whose
    only reference is the trie are reclaimable on allocation pressure
    (LRU leaf-first) and are reported as free by the pool.
    """

    def __init__(self, page_size):
        self.page = int(page_size)
        self._root = _PrefixNode()
        self._clock = 0

    def _bump(self):
        self._clock += 1
        return self._clock

    # ------------------------------------------------------------- read --
    def lookup(self, prompt):
        """Longest cached page-aligned prefix of `prompt`.

        Returns (pages, covered, partial, next_token): `pages` are the
        full shared page ids covering `covered - (partial and its len)`
        ... specifically full pages cover the first len(pages)*page
        tokens; `partial`, when not None, is (page_id, n_tokens) for a
        shared sub-page chunk extending the covered span (the caller
        must copy-on-write that page before appending); `next_token` is
        the cached greedy continuation when the WHOLE prompt is covered
        (else None)."""
        node = self._root
        pages = []
        m = 0
        n = len(prompt)
        for key in prefix_page_keys(prompt, self.page):
            child = node.children.get(key)
            if child is None:
                break
            child.last_use = self._bump()
            pages.append(child.page)
            m += self.page
            node = child
        next_token = node.next_token if (m == n and m > 0) else None
        partial = None
        if m < n:
            rem = tuple(prompt[m:])
            best = None
            for toks, rec in node.partials.items():
                if (len(toks) <= len(rem) and rem[:len(toks)] == toks
                        and (best is None or len(toks) > len(best[0]))):
                    best = (toks, rec)
            if best is not None:
                toks, rec = best
                rec[2] = self._bump()
                partial = (rec[0], len(toks))
                if m + len(toks) == n and rec[1] is not None:
                    next_token = rec[1]
        return pages, m, partial, next_token

    # ------------------------------------------------------------ write --
    def insert(self, prompt, page_ids, next_tokens, pool):
        """Record a freshly prefilled prompt. `page_ids`: the pages
        holding the prompt's K/V in order (ceil(len/page) entries, the
        request's own table prefix). `next_tokens[i]` is the greedy
        token after prompt position i (None where unknown, e.g. the
        already-cached prefix of a suffix prefill). Existing nodes are
        left untouched; new nodes retain their page in the pool."""
        node = self._root
        m, i, n = 0, 0, len(prompt)
        for chunk in prefix_page_keys(prompt, self.page):
            child = node.children.get(chunk)
            if child is None:
                nt = next_tokens[m + self.page - 1] if next_tokens else None
                child = _PrefixNode(page_ids[i], nt, self._bump())
                pool.retain([page_ids[i]])
                node.children[chunk] = child
            m += self.page
            i += 1
            node = child
        if m < n:
            rem = tuple(prompt[m:])
            if rem not in node.partials:
                nt = next_tokens[n - 1] if next_tokens else None
                node.partials[rem] = [page_ids[i], nt, self._bump()]
                pool.retain([page_ids[i]])

    # ---------------------------------------------------------- reclaim --
    def _droppable(self, pool):
        """Yield (last_use, kind, node, key) for every entry whose page
        the pool would actually free (trie holds the only reference)."""
        out = []

        def walk(node):
            for toks, rec in node.partials.items():
                if pool.ref_count(rec[0]) == 1:
                    out.append((rec[2], "partial", node, toks))
            for chunk, child in node.children.items():
                if (not child.children and not child.partials
                        and pool.ref_count(child.page) == 1):
                    out.append((child.last_use, "leaf", node, chunk))
                else:
                    walk(child)

        walk(self._root)
        return out

    def reclaimable_count(self, pool):
        """Pages the trie holds that no request is using (one linear
        walk). Slightly optimistic: a ref-1 interior node above a
        pinned descendant counts here but cannot actually be freed
        until the descendant's user evicts — `alloc` handles that by
        re-checking after `reclaim`, and once the pool is idle the
        count is exact (the leak-accounting case)."""
        count = 0

        def walk(node):
            nonlocal count
            for rec in node.partials.values():
                if pool.ref_count(rec[0]) == 1:
                    count += 1
            for child in node.children.values():
                if pool.ref_count(child.page) == 1:
                    count += 1
                walk(child)

        walk(self._root)
        return count

    def reclaim(self, pool, need):
        """Drop least-recently-used unpinned leaves until `need` pages
        were freed (or nothing droppable remains). Returns pages freed."""
        freed = 0
        while freed < need:
            cands = self._droppable(pool)
            if not cands:
                break
            cands.sort(key=lambda c: c[0])
            take = cands[:max(need - freed, 1)]
            for _, kind, parent, key in take:
                if kind == "partial":
                    rec = parent.partials.pop(key)
                    pool.release([rec[0]])
                else:
                    child = parent.children.pop(key)
                    pool.release([child.page])
                freed += 1
                if freed >= need:
                    break
        if freed:
            # page-eviction telemetry: cached-but-idle pages dropped
            # under allocation pressure. A sustained rate means the
            # pool is undersized for the working set — the signal
            # tools/autotune.py turns into a num_pages proposal.
            from ..observability import metrics as _obsm
            _obsm.counter("serving.page_evictions").inc(freed)
        return freed

    def clear(self, pool):
        """Release every cached page (used by tests and pool teardown)."""

        def walk(node):
            for rec in node.partials.values():
                pool.release([rec[0]])
            for child in node.children.values():
                walk(child)
                pool.release([child.page])

        walk(self._root)
        self._root = _PrefixNode()


class PagedCacheEntry(NamedTuple):
    """Per-layer paged KV cache (reference parity: the block KV layout of
    paddle/phi/kernels/fusion/gpu block_multihead_attention / vLLM).

    `k_pages`/`v_pages`: [num_pages, page_size, n_kv_heads, head_dim];
    `block_table`: [B, pages_per_seq] int32 page ids per slot;
    `context_lens`: [B] int32 tokens already cached per slot (BEFORE the
    token being decoded). `ragged_meta` (optional): host-built metadata
    from kernels.paged_attention.build_ragged_meta for the POST-write
    lengths (context_lens + 1) — when present, attention runs the
    ragged-grid kernel (only valid (seq, page) pairs enter the grid).

    `q_lens` (optional, [B] int32): per-slot QUERY SPAN lengths for the
    MIXED prefill+decode step — slot b's forward carries q_lens[b]
    tokens (a prefill chunk, or 1 for a decode tick) starting at
    absolute position context_lens[b]. When set, attention dispatches
    to `paged_cache_mixed_update_attend` (span K/V scatter + the
    variable-query ragged kernel) and `ragged_meta`, if present, must
    be built for the post-write lengths context_lens + q_lens.
    """
    k_pages: object
    v_pages: object
    block_table: object
    context_lens: object
    ragged_meta: object = None
    q_lens: object = None


class PagedKVCache:
    """A list of per-layer PagedCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[PagedCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def paged_cache_update_attend(entry: PagedCacheEntry, q, k, v, scale=None):
    """Decode-step contract for the paged cache: write this step's K/V
    (one token per slot) into each slot's current page position, then
    attend the query token against the slot's pages with the paged
    Pallas kernel. q: [B, 1, H, D]; k/v: [B, 1, Hkv, D] → (out
    [B, 1, H, D], updated entry). Gradients are not defined (serving
    path)."""
    import jax.numpy as jnp
    from ..ops._dispatch import apply
    from ..kernels.paged_attention import (paged_attention,
                                           paged_attention_ragged)

    if entry.q_lens is not None:
        # mixed prefill+decode step: variable-length query spans
        return paged_cache_mixed_update_attend(entry, q, k, v, scale)

    meta = entry.ragged_meta

    def fn(kp, vp, bt, cl, qv, kv, vv, *meta_arrs):
        bsz = qv.shape[0]
        page = kp.shape[1]
        rows = jnp.arange(bsz)
        pidx = bt[rows, (cl // page).astype(jnp.int32)]
        off = (cl % page).astype(jnp.int32)
        kp2 = kp.at[pidx, off].set(kv[:, 0].astype(kp.dtype))
        vp2 = vp.at[pidx, off].set(vv[:, 0].astype(vp.dtype))
        if meta_arrs:
            mk = dict(zip(("seq", "page", "ordinal", "first", "last",
                           "valid"), meta_arrs))
            out = paged_attention_ragged(qv[:, 0], kp2, vp2, cl + 1, mk,
                                         scale)
        else:
            out = paged_attention(qv[:, 0], kp2, vp2, bt, cl + 1, scale)
        return out[:, None].astype(qv.dtype), kp2, vp2

    extra = () if meta is None else tuple(
        meta[k] for k in ("seq", "page", "ordinal", "first", "last",
                          "valid"))
    out, kp2, vp2 = apply(fn, entry.k_pages, entry.v_pages,
                          entry.block_table, entry.context_lens, q, k, v,
                          *extra, _name="paged_attention_decode")
    new_entry = PagedCacheEntry(kp2, vp2, entry.block_table,
                                entry.context_lens, entry.ragged_meta)
    return out, new_entry


def paged_cache_mixed_update_attend(entry: PagedCacheEntry, q, k, v,
                                    scale=None):
    """MIXED-step contract for the paged cache: each slot carries a
    query span of entry.q_lens[b] tokens (a prefill chunk, or 1 for a
    decode tick) starting at absolute position entry.context_lens[b].
    The span's K/V is scattered into the slot's pages IN-GRAPH, then
    the span attends causally over the pages with the variable-query
    ragged kernel (kernels.paged_attention.paged_attention_ragged_varq)
    — one compiled step serves a batch mixing mid-prefill and
    mid-decode requests. q: [B, Qb, H, D]; k/v: [B, Qb, Hkv, D] →
    (out [B, Qb, H, D], updated entry). Padding span positions (i >=
    q_lens[b]) write nothing (the scatter keeps the old page contents)
    and read back zeros. Gradients are not defined (serving path)."""
    import jax.numpy as jnp
    from ..ops._dispatch import apply
    from ..kernels.paged_attention import (paged_attention_varq,
                                           paged_attention_ragged_varq)

    meta = entry.ragged_meta

    def fn(kp, vp, bt, cl, ql, qv, kv, vv, *meta_arrs):
        qb = qv.shape[1]
        page = kp.shape[1]
        i = jnp.arange(qb, dtype=jnp.int32)[None, :]
        pos = cl[:, None].astype(jnp.int32) + i            # [B, Qb]
        writing = i < ql[:, None].astype(jnp.int32)        # [B, Qb]
        pslot = jnp.clip(pos // page, 0, bt.shape[1] - 1)
        # padding span positions write NOTHING: their destination page
        # is forced out of bounds and the scatter drops them. (Writing
        # their own gathered contents back instead would race: a
        # padding position past the END of a fully-allocated table
        # clips into the slot's last real page, and duplicate scatter
        # indices carrying different values — stale gather vs this
        # step's real K/V — have an unspecified winner.)
        dst_page = jnp.where(writing,
                             jnp.take_along_axis(bt, pslot, axis=1),
                             jnp.int32(kp.shape[0]))       # [B, Qb]
        dst_off = (pos % page).astype(jnp.int32)
        kp2 = kp.at[dst_page, dst_off].set(kv.astype(kp.dtype),
                                           mode="drop")
        vp2 = vp.at[dst_page, dst_off].set(vv.astype(vp.dtype),
                                           mode="drop")
        kv_lens = cl.astype(jnp.int32) + ql.astype(jnp.int32)
        if meta_arrs:
            mk = dict(zip(("seq", "page", "ordinal", "first", "last",
                           "valid"), meta_arrs))
            out = paged_attention_ragged_varq(qv, kp2, vp2, kv_lens, ql,
                                              mk, scale, block_tables=bt)
        else:
            out = paged_attention_varq(qv, kp2, vp2, bt, kv_lens, ql,
                                       scale)
        return out.astype(qv.dtype), kp2, vp2

    extra = () if meta is None else tuple(
        meta[k] for k in ("seq", "page", "ordinal", "first", "last",
                          "valid"))
    out, kp2, vp2 = apply(fn, entry.k_pages, entry.v_pages,
                          entry.block_table, entry.context_lens,
                          entry.q_lens, q, k, v, *extra,
                          _name="paged_attention_mixed")
    new_entry = PagedCacheEntry(kp2, vp2, entry.block_table,
                                entry.context_lens, entry.ragged_meta,
                                entry.q_lens)
    return out, new_entry
