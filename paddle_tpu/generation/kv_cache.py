"""Static-shape KV cache for XLA-friendly autoregressive decoding.

Reference parity: PaddleNLP generation caches (paddlenlp/transformers/
generation_utils.py `past_key_values`) and the fused block-attention
cache layout of paddle/phi/kernels/fusion/gpu (block_multihead_attention).

TPU-native design: instead of concatenating K/V each step (dynamic shapes
— retrace/recompile every token), the cache is a preallocated
[B, max_len, n_kv_heads, head_dim] buffer per layer written in place with
`lax.dynamic_update_slice` at a traced position. The whole decode loop
then compiles to ONE XLA program (`lax.scan` over steps) with static
shapes, which is the canonical TPU serving pattern.
"""
from __future__ import annotations

from typing import List, NamedTuple


class StaticCacheEntry(NamedTuple):
    """Per-layer cache entry: full K/V buffers plus the write position.

    `k`/`v` are Tensors (or traced arrays) of shape
    [batch, max_len, n_kv_heads, head_dim]; `pos` is a scalar int32
    Tensor — the slot where this step's keys/values are written.
    """
    k: object
    v: object
    pos: object


class StaticKVCache:
    """A list of per-layer StaticCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[StaticCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def static_cache_update(entry: StaticCacheEntry, k, v):
    """Write K/V ([B, s, H, D] Tensors) into the static cache at
    entry.pos (lax.dynamic_update_slice) — THE cache-write contract,
    shared by every model family's attention."""
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import apply

    def upd(cache, new, p):
        z = jnp.int32(0)
        return jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype),
            (z, p.astype(jnp.int32), z, z))

    k_new = apply(upd, entry.k, k, entry.pos, _name="kv_cache_update")
    v_new = apply(upd, entry.v, v, entry.pos, _name="kv_cache_update")
    return k_new, v_new, StaticCacheEntry(k_new, v_new, entry.pos)


class PagedCacheEntry(NamedTuple):
    """Per-layer paged KV cache (reference parity: the block KV layout of
    paddle/phi/kernels/fusion/gpu block_multihead_attention / vLLM).

    `k_pages`/`v_pages`: [num_pages, page_size, n_kv_heads, head_dim];
    `block_table`: [B, pages_per_seq] int32 page ids per slot;
    `context_lens`: [B] int32 tokens already cached per slot (BEFORE the
    token being decoded). `ragged_meta` (optional): host-built metadata
    from kernels.paged_attention.build_ragged_meta for the POST-write
    lengths (context_lens + 1) — when present, attention runs the
    ragged-grid kernel (only valid (seq, page) pairs enter the grid).
    """
    k_pages: object
    v_pages: object
    block_table: object
    context_lens: object
    ragged_meta: object = None


class PagedKVCache:
    """A list of per-layer PagedCacheEntry, passed as `past_key_values`."""

    def __init__(self, entries: List[PagedCacheEntry]):
        self.entries = entries

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __iter__(self):
        return iter(self.entries)


def paged_cache_update_attend(entry: PagedCacheEntry, q, k, v, scale=None):
    """Decode-step contract for the paged cache: write this step's K/V
    (one token per slot) into each slot's current page position, then
    attend the query token against the slot's pages with the paged
    Pallas kernel. q: [B, 1, H, D]; k/v: [B, 1, Hkv, D] → (out
    [B, 1, H, D], updated entry). Gradients are not defined (serving
    path)."""
    import jax.numpy as jnp
    from ..ops._dispatch import apply
    from ..kernels.paged_attention import (paged_attention,
                                           paged_attention_ragged)

    meta = entry.ragged_meta

    def fn(kp, vp, bt, cl, qv, kv, vv, *meta_arrs):
        bsz = qv.shape[0]
        page = kp.shape[1]
        rows = jnp.arange(bsz)
        pidx = bt[rows, (cl // page).astype(jnp.int32)]
        off = (cl % page).astype(jnp.int32)
        kp2 = kp.at[pidx, off].set(kv[:, 0].astype(kp.dtype))
        vp2 = vp.at[pidx, off].set(vv[:, 0].astype(vp.dtype))
        if meta_arrs:
            mk = dict(zip(("seq", "page", "ordinal", "first", "last",
                           "valid"), meta_arrs))
            out = paged_attention_ragged(qv[:, 0], kp2, vp2, cl + 1, mk,
                                         scale)
        else:
            out = paged_attention(qv[:, 0], kp2, vp2, bt, cl + 1, scale)
        return out[:, None].astype(qv.dtype), kp2, vp2

    extra = () if meta is None else tuple(
        meta[k] for k in ("seq", "page", "ordinal", "first", "last",
                          "valid"))
    out, kp2, vp2 = apply(fn, entry.k_pages, entry.v_pages,
                          entry.block_table, entry.context_lens, q, k, v,
                          *extra, _name="paged_attention_decode")
    new_entry = PagedCacheEntry(kp2, vp2, entry.block_table,
                                entry.context_lens, entry.ragged_meta)
    return out, new_entry
