"""Autoregressive generation — the TPU-native serving loop.

Reference parity: PaddleNLP paddlenlp/generation/utils.py
(`GenerationMixin.generate` with decode_strategy greedy_search/sampling,
top_k/top_p/temperature/repetition_penalty, eos early-exit) and the
fused-cache inference path of paddle/phi/kernels/fusion/gpu.

TPU-native design (NOT a port of the reference's dynamic python loop):
- one jitted XLA program per (batch, prompt_len, max_new_tokens) bucket:
  prefill + `lax.scan` decode over a static-shape KV cache
  (kv_cache.StaticKVCache, written via lax.dynamic_update_slice);
- ragged prompts handled by LEFT padding + position_ids derived from the
  attention mask, so every row's last prompt token sits at the same slot
  and the decode loop is fully uniform (no per-row control flow);
- eos early-stop expressed as a `finished` lane mask (tokens after eos
  become pad) — scan length stays static, XLA-friendly;
- models opt in via `supports_static_cache`; others fall back to an
  eager full-recompute loop (correct, slower).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .kv_cache import StaticCacheEntry, StaticKVCache
from . import logits_process as LP

__all__ = ["GenerationConfig", "GenerationMixin", "StaticCacheEntry",
           "StaticKVCache"]


@dataclass
class GenerationConfig:
    """Knob bag mirroring PaddleNLP GenerationConfig field names."""
    max_new_tokens: int = 32
    min_new_tokens: int = 0
    decode_strategy: str = "greedy_search"  # "sampling" | "beam_search"
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    num_beams: int = 1
    length_penalty: float = 0.0
    # accepted for config parity: with frozen-finished-beam semantics the
    # search result is identical either way; once every beam is finished
    # both implementations skip the remaining model calls automatically
    early_stopping: bool = False
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    use_cache: bool = True
    seed: Optional[int] = None


def _left_pad(ids: np.ndarray, mask: np.ndarray, pad_id: int):
    """Roll each row so padding sits on the left (decoder-only layout)."""
    out_ids = np.full_like(ids, pad_id)
    out_mask = np.zeros_like(mask)
    n = ids.shape[1]
    for b in range(ids.shape[0]):
        keep = ids[b][mask[b].astype(bool)]
        out_ids[b, n - len(keep):] = keep
        out_mask[b, n - len(keep):] = 1
    return out_ids, out_mask


class GenerationMixin:
    """Adds `.generate()` to causal-LM Layers."""

    supports_static_cache = False

    # -- model hooks (overridable) ---------------------------------------
    def _cache_spec(self):
        cfg = self.config
        n_kv = getattr(cfg, "num_key_value_heads", None) or \
            cfg.num_attention_heads
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        return cfg.num_hidden_layers, n_kv, head_dim

    def _cache_dtype(self):
        for p in self.parameters():
            return p.dtype
        return jnp.float32

    # -- public API ------------------------------------------------------
    def generate(self, input_ids, attention_mask=None, generation_config=None,
                 **kwargs):
        """Returns (generated_ids [B, max_new_tokens], scores [B]).

        `generated_ids` contains only NEW tokens (PaddleNLP convention);
        positions after eos are pad_token_id. For greedy/sampling,
        `scores` is the mean logprob of the emitted tokens; for
        beam_search it is the best beam's cumulative logprob normalized
        by the GNMT length penalty ((5+len)/6)**length_penalty — the two
        are different quantities (beam semantics follow the reference)
        and should not be compared across strategies.
        """
        from ..tensor import Tensor

        import dataclasses
        cfg = (dataclasses.replace(generation_config)
               if generation_config is not None else GenerationConfig())
        for k, v in kwargs.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)

        ids = np.asarray(input_ids.numpy() if isinstance(input_ids, Tensor)
                         else input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        if attention_mask is None:
            mask = np.ones_like(ids, dtype=np.int32)
        else:
            mask = np.asarray(
                attention_mask.numpy()
                if isinstance(attention_mask, Tensor) else attention_mask
            ).astype(np.int32)
        if cfg.seed is not None:
            base_seed = int(cfg.seed)
        else:
            # fresh randomness from the global generator (paddle.seed):
            # one host draw anchors the whole call's counter-based key
            # streams (generation/sampling.py) — row r of the batch
            # seeds at base_seed + r, token t draws with counter t, so
            # the SAME seed replayed through the eager, static, or
            # serve-loop path yields the same sampled tokens
            from ..framework.random import next_key
            base_seed = int(jax.random.randint(
                next_key(), (), 0, np.int32(2 ** 31 - 1)))

        beam = cfg.decode_strategy == "beam_search"
        if not beam and (cfg.num_beams or 1) > 1:
            # PaddleNLP raises for greedy/sampling with num_beams > 1 —
            # silently ignoring either knob would mislead
            raise ValueError(
                f"num_beams={cfg.num_beams} requires "
                "decode_strategy='beam_search' (got "
                f"{cfg.decode_strategy!r})")
        if beam and cfg.use_cache and self.supports_static_cache:
            if (mask == 0).any():
                ids, mask = _left_pad(ids, mask, cfg.pad_token_id)
            out, scores = self._generate_beam(ids, mask, cfg)
        elif beam:
            out, scores = self._generate_beam_eager(ids, mask, cfg)
        elif cfg.use_cache and self.supports_static_cache:
            # decoder-only layout: padding goes on the LEFT so every
            # row's last prompt token shares one slot
            if (mask == 0).any():
                ids, mask = _left_pad(ids, mask, cfg.pad_token_id)
            out, scores = self._generate_static(ids, mask, base_seed,
                                                cfg)
        else:
            out, scores = self._generate_eager(ids, mask, base_seed,
                                               cfg)
        return Tensor(out), Tensor(scores)

    # -- jitted static-cache path ----------------------------------------
    def _generate_static(self, ids, mask, base_seed, cfg):
        from ..jit.bridge import functionalize
        from ..autograd.grad_mode import no_grad

        n_layers, n_kv, head_dim = self._cache_spec()
        B, S = ids.shape
        N = int(cfg.max_new_tokens)
        ML = S + N
        greedy = cfg.decode_strategy in ("greedy_search", "greedy")
        sig = (B, S, N, greedy, cfg.top_k, cfg.eos_token_id,
               cfg.pad_token_id, cfg.min_new_tokens,
               float(cfg.temperature), float(cfg.top_p),
               float(cfg.repetition_penalty))
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        if sig not in cache:
            cache[sig] = self._build_static_fn(
                n_layers, n_kv, head_dim, B, S, N, ML, greedy, cfg)
        fn = cache[sig]
        # rebind the CURRENT weights each call — the compiled fn is pure
        # in (params, buffers), so checkpoint reloads / further training
        # are picked up without retracing
        p_vals = [p._value for _, p in self.named_parameters()]
        b_vals = [b._value for _, b in self.named_buffers()]
        seeds = jnp.asarray(base_seed + np.arange(B), jnp.int32)
        with no_grad():
            out, scores = fn(p_vals, b_vals, jnp.asarray(ids, jnp.int32),
                             jnp.asarray(mask, jnp.int32), seeds)
        return np.asarray(out), np.asarray(scores)

    def _make_cache_runner(self, n_layers):
        """Functionalize the cached forward ONCE: returns run_model(p, b,
        ids2d, amask, posid, cachepos, kv) -> (logits, new_kv). Shared
        cache/attention plumbing for the greedy/sampling AND beam
        builders — fix it here, both paths get it."""
        from ..jit.bridge import functionalize
        from ..tensor import Tensor

        was_training = self.training
        self.eval()

        def model_fn(ids_t, amask_t, posid_t, cachepos_t, *flat_kv):
            entries = [StaticCacheEntry(flat_kv[2 * i], flat_kv[2 * i + 1],
                                        cachepos_t)
                       for i in range(n_layers)]
            logits, new_entries = self.forward(
                ids_t, attn_mask=amask_t, position_ids=posid_t,
                past_key_values=StaticKVCache(entries), use_cache=True)
            flat = [logits]
            for e in new_entries:
                flat.append(e.k)
                flat.append(e.v)
            return flat

        pure_fn, _, _, _, _ = functionalize(self, fn=model_fn,
                                            training=False)
        if was_training:
            self.train()

        def run_model(p, b, ids2d, amask, posid, cachepos, kv):
            outs, _, _ = pure_fn(p, b, jax.random.key(0),
                                 Tensor(ids2d), Tensor(amask), Tensor(posid),
                                 Tensor(cachepos), *[Tensor(x) for x in kv])
            logits = outs[0]._value
            new_kv = [t._value for t in outs[1:]]
            return logits, new_kv
        return run_model

    @staticmethod
    def _cache_prefill(run_model, p, b, ids, mask, n_layers, n_kv,
                       head_dim, ML, dtype):
        """Zero-init the [rows, ML, ...] cache, build the causal+padding
        prefill mask, and run the prompt pass. Returns
        (logits, kv, kmask, posid)."""
        rows, S = ids.shape
        posid = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0)
        kv = []
        for _ in range(n_layers):
            kv.append(jnp.zeros((rows, ML, n_kv, head_dim), dtype))
            kv.append(jnp.zeros((rows, ML, n_kv, head_dim), dtype))
        kmask = jnp.concatenate(
            [mask.astype(bool), jnp.zeros((rows, ML - S), bool)], axis=1)
        i_ids = jnp.arange(S)[:, None]
        j_ids = jnp.arange(ML)[None, :]
        amask = ((j_ids <= i_ids)[None, None]
                 & kmask[:, None, None, :])  # [rows,1,S,ML]
        logits, kv = run_model(p, b, ids, amask, posid, jnp.int32(0), kv)
        return logits, kv, kmask, posid

    def _build_static_fn(self, n_layers, n_kv, head_dim, B, S, N, ML,
                         greedy, cfg):
        dtype = self._cache_dtype()
        eos = cfg.eos_token_id
        pad = cfg.pad_token_id
        temperature, top_k, top_p = cfg.temperature, cfg.top_k, cfg.top_p
        rep_pen = cfg.repetition_penalty
        min_new = cfg.min_new_tokens
        vocab = self.config.vocab_size
        track_counts = rep_pen != 1.0
        run_model = self._make_cache_runner(n_layers)

        from . import sampling as SK

        def sample_step(logits, seeds, counts, step_idx):
            # the SHARED on-device sampling kernel (generation/
            # sampling.py): temperature/top-k/top-p as operands, keys
            # from fold_in(key(seed), token_index) — the serve loop
            # runs the identical kernel with per-request operands, so
            # a fixed seed yields the same stream on either path
            lg = logits.astype(jnp.float32)
            lg = LP.min_length_mask(lg, step_idx, min_new, eos)
            if track_counts and rep_pen != 1.0:
                lg = LP.repetition_penalty(lg, counts, rep_pen)
            tok, logp = SK.sample_tokens(
                lg,
                jnp.full((B,), 0.0 if greedy else float(temperature),
                         jnp.float32),
                jnp.full((B,), int(top_k), jnp.int32),
                jnp.full((B,), float(top_p), jnp.float32),
                seeds,
                jnp.broadcast_to(jnp.asarray(step_idx, jnp.int32),
                                 (B,)))
            return tok, logp

        def raw(p, b, ids, mask, seeds):
            real_len = jnp.sum(mask, axis=1)  # [B]
            logits, kv, kmask, _ = self._cache_prefill(
                run_model, p, b, ids, mask, n_layers, n_kv, head_dim,
                ML, dtype)
            counts = (jnp.zeros((B, vocab), jnp.int32)
                      .at[jnp.arange(B)[:, None], ids].add(
                          mask.astype(jnp.int32))
                      if track_counts else jnp.zeros((B, 1), jnp.int32))
            tok0, logp0 = sample_step(
                logits[:, -1, :], seeds, counts, jnp.int32(0))
            finished0 = (tok0 == eos) if eos is not None \
                else jnp.zeros((B,), bool)
            if track_counts:
                counts = counts.at[jnp.arange(B), tok0].add(1)

            def body(carry, step):
                tok, kvs, km, fin, cnt = carry
                slot = S + step
                km = jax.lax.dynamic_update_slice(
                    km, jnp.ones((B, 1), bool),
                    (jnp.int32(0), slot.astype(jnp.int32)))
                am = km[:, None, None, :]
                pid = (real_len + step)[:, None]
                lg, kvs = run_model(p, b, tok[:, None], am, pid, slot, kvs)
                ntok, nlogp = sample_step(lg[:, -1, :], seeds, cnt,
                                          step + 1)
                if eos is not None:
                    newly_fin = fin | (ntok == eos)
                else:
                    newly_fin = fin
                emit = jnp.where(fin, jnp.int32(pad), ntok)
                elogp = jnp.where(fin, 0.0, nlogp)
                if track_counts:
                    cnt = cnt.at[jnp.arange(B), emit].add(
                        (~fin).astype(jnp.int32))
                return (emit, kvs, km, newly_fin, cnt), (emit, elogp)

            if N > 1:
                init = (tok0, kv, kmask, finished0, counts)
                _, (toks, logps) = jax.lax.scan(
                    body, init, jnp.arange(N - 1, dtype=jnp.int32))
                all_toks = jnp.concatenate(
                    [tok0[:, None], toks.T.astype(jnp.int32)], axis=1)
                all_logps = jnp.concatenate(
                    [logp0[:, None], logps.T], axis=1)
            else:
                all_toks = tok0[:, None]
                all_logps = logp0[:, None]
            emitted = all_toks != pad
            denom = jnp.maximum(jnp.sum(emitted, axis=1), 1)
            scores = jnp.sum(all_logps * emitted, axis=1) / denom
            return all_toks, scores

        return jax.jit(raw)

    # -- eager fallback (no cache protocol needed) -----------------------
    def _generate_eager(self, ids, mask, base_seed, cfg):
        # plain `forward(input_ids)` has no mask/position inputs, so a
        # padded batch would attend pad tokens at shifted positions —
        # run each ragged row on its own (correctness over speed; the
        # static-cache path is the fast ragged-batch route). Row b
        # seeds at base_seed + b, matching the batched path's
        # per-row seed layout.
        if (mask == 0).any():
            outs, scores = [], []
            for b in range(ids.shape[0]):
                row = ids[b][mask[b].astype(bool)][None, :]
                o, s = self._generate_eager(
                    row, np.ones_like(row, dtype=np.int32),
                    base_seed + b, cfg)
                outs.append(o[0])
                scores.append(s[0])
            return np.stack(outs), np.asarray(scores, np.float32)
        return self._generate_eager_batch(ids, mask, base_seed, cfg)

    def _generate_eager_batch(self, ids, mask, base_seed, cfg):
        from ..tensor import Tensor
        from ..autograd.grad_mode import no_grad
        from . import sampling as SK

        greedy = cfg.decode_strategy in ("greedy_search", "greedy")
        B = ids.shape[0]
        s_temp = np.full((B,), 0.0 if greedy else float(cfg.temperature),
                         np.float32)
        s_topk = np.full((B,), int(cfg.top_k), np.int32)
        s_topp = np.full((B,), float(cfg.top_p), np.float32)
        s_seed = (int(base_seed) + np.arange(B)).astype(np.int32)
        # graft-lint: ok[GL102] — ids is the caller's host array
        # (numpy->numpy normalization, not a device download)
        cur = np.asarray(ids)
        finished = np.zeros((B,), bool)
        outs, logps = [], []
        counts = None
        if cfg.repetition_penalty != 1.0:
            counts = np.zeros((B, self.config.vocab_size), np.int32)
            for b in range(B):
                np.add.at(counts[b], cur[b][mask[b].astype(bool)], 1)
        with no_grad():
            for step in range(cfg.max_new_tokens):
                out = self.forward(Tensor(jnp.asarray(cur, jnp.int32)))
                # last position sliced ON DEVICE: downloading the full
                # [B, S, V] logits and re-uploading the slice cost two
                # transfers of the largest tensor in the loop per token
                # (caught by graft-lint GL102)
                lg = (out[0] if isinstance(out, tuple)
                      else out)._value[:, -1, :].astype(jnp.float32)
                lg = LP.min_length_mask(lg, step, cfg.min_new_tokens,
                                        cfg.eos_token_id)
                if counts is not None and cfg.repetition_penalty != 1.0:
                    lg = LP.repetition_penalty(
                        lg, jnp.asarray(counts), cfg.repetition_penalty)
                # the SHARED sampling kernel (generation/sampling.py):
                # counter-based keys — token `step` of row b draws with
                # fold_in(key(base_seed + b), step), the same stream
                # the static path and the serve loop use
                tok, logp = SK.sample_tokens(
                    lg, s_temp, s_topk, s_topp, s_seed,
                    np.full((B,), step, np.int32))
                # graft-lint: ok[GL102] — THE designed per-token sync
                # of the eager path: two [B] vectors drive the
                # host-side eos/penalty bookkeeping
                tok = np.asarray(tok)
                logp = np.asarray(logp)  # graft-lint: ok[GL102] (ditto)
                emit = np.where(finished, cfg.pad_token_id, tok)
                logps.append(np.where(finished, 0.0, logp))
                outs.append(emit)
                if cfg.eos_token_id is not None:
                    finished |= tok == cfg.eos_token_id
                if counts is not None:
                    np.add.at(counts, (np.arange(B), emit),
                              (~finished).astype(np.int32))
                cur = np.concatenate([cur, emit[:, None]], axis=1)
                if finished.all():
                    break
        toks = np.stack(outs, axis=1).astype(np.int32)
        if toks.shape[1] < cfg.max_new_tokens:  # pad early-stopped batches
            padw = cfg.max_new_tokens - toks.shape[1]
            toks = np.pad(toks, ((0, 0), (0, padw)),
                          constant_values=cfg.pad_token_id)
        lp = np.stack(logps, axis=1)
        emitted = toks[:, :lp.shape[1]] != cfg.pad_token_id
        denom = np.maximum(emitted.sum(axis=1), 1)
        scores = (lp * emitted).sum(axis=1) / denom
        return toks, scores.astype(np.float32)

    # -- beam search ------------------------------------------------------
    def _generate_beam(self, ids, mask, cfg):
        """Jitted beam search over the static KV cache: beams live as
        extra batch rows ([B*K, ...]), each step reorders the cache by
        the selected parent beams with one gather (parity:
        PaddleNLP generation beam_search; upstream
        python/paddle/nn/decode.py BeamSearchDecoder semantics —
        GNMT-style length normalization score/((5+len)/6)**lp)."""
        from ..autograd.grad_mode import no_grad

        n_layers, n_kv, head_dim = self._cache_spec()
        B, S = ids.shape
        K = int(cfg.num_beams)
        N = int(cfg.max_new_tokens)
        ML = S + N
        sig = ("beam", B, S, N, K, cfg.eos_token_id, cfg.pad_token_id,
               float(cfg.length_penalty), cfg.min_new_tokens)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        if sig not in cache:
            cache[sig] = self._build_beam_fn(n_layers, n_kv, head_dim,
                                             B, S, N, ML, K, cfg)
        fn = cache[sig]
        p_vals = [p._value for _, p in self.named_parameters()]
        b_vals = [b._value for _, b in self.named_buffers()]
        with no_grad():
            out, scores = fn(p_vals, b_vals, jnp.asarray(ids, jnp.int32),
                             jnp.asarray(mask, jnp.int32))
        return np.asarray(out), np.asarray(scores)

    def _build_beam_fn(self, n_layers, n_kv, head_dim, B, S, N, ML, K,
                       cfg):
        dtype = self._cache_dtype()
        eos = cfg.eos_token_id
        pad = cfg.pad_token_id
        lp_exp = float(cfg.length_penalty)
        min_new = cfg.min_new_tokens
        vocab = self.config.vocab_size
        BK = B * K
        NEG = jnp.float32(-1e9)
        run_model = self._make_cache_runner(n_layers)

        def lnorm(length):
            # GNMT: ((5 + len) / 6) ** length_penalty
            return ((5.0 + length.astype(jnp.float32)) / 6.0) ** lp_exp

        def raw(p, b, ids, mask):
            # prefill on [B, S] ONCE, then replicate the kv cache to the
            # beam rows ([B*K, ...]; row b*K + j is beam j of sequence b)
            # — all beams start identical, so K prefill passes would be
            # K-1 wasted forwards
            logits, kv, kmask1, _ = self._cache_prefill(
                run_model, p, b, ids, mask, n_layers, n_kv, head_dim,
                ML, dtype)
            kv = [jnp.repeat(a, K, axis=0) for a in kv]  # [BK, ...]
            kmask = jnp.repeat(kmask1, K, axis=0)
            real_len = jnp.repeat(jnp.sum(mask, axis=1), K)  # [BK]
            logp0 = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32), axis=-1)
            if eos is not None and min_new > 0:
                logp0 = logp0.at[:, eos].set(NEG)
            first = logp0                                # [B, V]
            top_lp, top_tok = jax.lax.top_k(first, K)    # [B, K]
            beam_scores = top_lp                         # [B, K]
            tokens0 = top_tok.astype(jnp.int32)
            finished0 = ((tokens0 == eos) if eos is not None
                         else jnp.zeros((B, K), bool))
            hist0 = jnp.full((B, K, N), pad, jnp.int32)
            hist0 = hist0.at[:, :, 0].set(tokens0)

            def step(carry, t):
                # all-finished short-circuit: skip the model call (and
                # reorders) once nothing can change — lax.cond picks the
                # cheap branch at runtime inside the scan
                return jax.lax.cond(jnp.all(carry[2]),
                                    lambda c: (c, None),
                                    lambda c: (_live_step(c, t), None),
                                    carry)

            def _live_step(carry, t):
                tok, scores, fin, hist, kvs, km = carry
                # tok [B,K] current last token per beam
                slot = S + t
                km = jax.lax.dynamic_update_slice(
                    km, jnp.ones((BK, 1), bool),
                    (jnp.int32(0), slot.astype(jnp.int32)))
                am = km[:, None, None, :]
                pid = (real_len + t)[:, None]
                lg, kvs = run_model(p, b, tok.reshape(BK, 1), am, pid,
                                    slot, kvs)
                logp = jax.nn.log_softmax(
                    lg[:, -1, :].astype(jnp.float32), axis=-1)
                logp = logp.reshape(B, K, vocab)
                if eos is not None and min_new > 0:
                    logp = jnp.where(
                        (t + 1 < min_new),
                        logp.at[:, :, eos].set(NEG), logp)
                # finished beams: freeze (only pad continuation, no cost)
                cont = scores[:, :, None] + logp         # [B,K,V]
                frozen = jnp.full((B, K, vocab), NEG)
                frozen = frozen.at[:, :, pad].set(scores)
                cand = jnp.where(fin[:, :, None], frozen, cont)
                flat = cand.reshape(B, K * vocab)
                best, idx = jax.lax.top_k(flat, K)       # [B,K]
                parent = (idx // vocab).astype(jnp.int32)
                ntok = (idx % vocab).astype(jnp.int32)
                # reorder everything by parent beam
                gat = (jnp.arange(B)[:, None] * K + parent).reshape(BK)
                kvs = [a[gat] for a in kvs]
                km = km[gat]
                hist = jnp.take_along_axis(
                    hist, parent[:, :, None], axis=1)
                fin = jnp.take_along_axis(fin, parent, axis=1)
                emit = jnp.where(fin, jnp.int32(pad), ntok)
                hist = hist.at[:, :, t + 1].set(emit)
                if eos is not None:
                    fin = fin | (ntok == eos)
                return (emit, best, fin, hist, kvs, km)

            carry = (tokens0, beam_scores, finished0, hist0, kv, kmask)
            if N > 1:
                carry, _ = jax.lax.scan(
                    step, carry, jnp.arange(N - 1, dtype=jnp.int32))
            _, scores, fin, hist, _, _ = carry
            # length-normalized final ranking
            lens = jnp.sum(hist != pad, axis=2)          # [B,K]
            norm = scores / lnorm(jnp.maximum(lens, 1))
            best = jnp.argmax(norm, axis=1)              # [B]
            out = jnp.take_along_axis(
                hist, best[:, None, None], axis=1)[:, 0]
            sc = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
            return out, sc

        return jax.jit(raw)

    def _generate_beam_eager(self, ids, mask, cfg):
        """Eager beam search (no cache protocol): beams as batch rows,
        full-prefix recompute per step. Correctness-first fallback for
        models without static-cache support."""
        from ..tensor import Tensor
        from ..autograd.grad_mode import no_grad

        if (mask == 0).any():
            outs, scores = [], []
            for b in range(ids.shape[0]):
                row = ids[b][mask[b].astype(bool)][None, :]
                o, s = self._generate_beam_eager(
                    row, np.ones_like(row, dtype=np.int32), cfg)
                outs.append(o[0])
                scores.append(s[0])
            return np.stack(outs), np.asarray(scores, np.float32)

        B, S = ids.shape
        K = int(cfg.num_beams)
        N = int(cfg.max_new_tokens)
        eos, pad = cfg.eos_token_id, cfg.pad_token_id
        vocab = self.config.vocab_size
        NEG = np.float32(-1e9)
        cur = np.repeat(np.asarray(ids), K, axis=0)       # [B*K, S+t]
        beam_scores = np.full((B, K), NEG, np.float32)
        beam_scores[:, 0] = 0.0
        finished = np.zeros((B, K), bool)
        hist = np.full((B, K, N), pad, np.int32)
        with no_grad():
            for t in range(N):
                out = self.forward(Tensor(jnp.asarray(cur, jnp.int32)))
                logits = np.asarray((out[0] if isinstance(out, tuple)
                                     else out)._value)[:, -1, :]
                # np.array (copy): np.asarray of a jax buffer is
                # read-only and the eos mask below writes in place
                logp = np.array(jax.nn.log_softmax(
                    jnp.asarray(logits, jnp.float32), axis=-1))
                logp = logp.reshape(B, K, vocab)
                if eos is not None and t < cfg.min_new_tokens:
                    logp[:, :, eos] = NEG
                cont = beam_scores[:, :, None] + logp
                frozen = np.full((B, K, vocab), NEG, np.float32)
                frozen[:, :, pad] = beam_scores
                cand = np.where(finished[:, :, None], frozen, cont)
                flat = cand.reshape(B, K * vocab)
                idx = np.argsort(-flat, axis=1)[:, :K]
                beam_scores = np.take_along_axis(flat, idx, axis=1)
                parent = idx // vocab
                ntok = (idx % vocab).astype(np.int32)
                gat = (np.arange(B)[:, None] * K + parent).reshape(-1)
                cur = cur[gat]
                hist = np.take_along_axis(hist, parent[:, :, None],
                                          axis=1)
                finished = np.take_along_axis(finished, parent, axis=1)
                emit = np.where(finished, pad, ntok)
                hist[:, :, t] = emit
                if eos is not None:
                    finished |= ntok == eos
                cur = np.concatenate([cur, emit.reshape(-1, 1)], axis=1)
                if finished.all():
                    break
        lens = (hist != pad).sum(axis=2)
        lp_exp = float(cfg.length_penalty)
        norm = beam_scores / (((5.0 + np.maximum(lens, 1)) / 6.0)
                              ** lp_exp)
        best = np.argmax(norm, axis=1)
        out = np.take_along_axis(hist, best[:, None, None],
                                 axis=1)[:, 0]
        sc = np.take_along_axis(norm, best[:, None], axis=1)[:, 0]
        return out.astype(np.int32), sc.astype(np.float32)
