"""paddle.fft parity — discrete Fourier transform family.

Reference parity: python/paddle/fft.py (which lowers to phi fft kernels,
cuFFT on GPU). On TPU the transforms lower to XLA FFT HLOs directly via
jnp.fft; autograd flows through the standard apply() vjp path (jax has
complex-differentiable FFT rules).

Paddle semantics kept: `norm` in {"backward","ortho","forward"}; `n`/`s`
pad-or-truncate; `axis`/`axes` selection; real transforms (rfft family)
return the half spectrum.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops._dispatch import apply
from .ops.creation import _coerce
from .tensor import Tensor

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "hfftn", "ihfft2", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(f"invalid norm {norm!r}")
    return norm


def _unary(name, jfn, x, *, n=None, axis=-1, norm=None):
    return apply(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)),
                 _coerce(x), _name=name)


def _nary(name, jfn, x, *, s=None, axes=None, norm=None):
    return apply(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                 _coerce(x), _name=name)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary("fft", jnp.fft.fft, x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary("ifft", jnp.fft.ifft, x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary("rfft", jnp.fft.rfft, x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary("irfft", jnp.fft.irfft, x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary("hfft", jnp.fft.hfft, x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary("ihfft", jnp.fft.ihfft, x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nary("fft2", jnp.fft.fft2, x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nary("ifft2", jnp.fft.ifft2, x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nary("rfft2", jnp.fft.rfft2, x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _nary("irfft2", jnp.fft.irfft2, x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _nary("fftn", jnp.fft.fftn, x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _nary("ifftn", jnp.fft.ifftn, x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _nary("rfftn", jnp.fft.rfftn, x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _nary("irfftn", jnp.fft.irfftn, x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d)
    if dtype is not None:
        from .framework.dtype import convert_dtype as to_jax_dtype
        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d)
    if dtype is not None:
        from .framework.dtype import convert_dtype as to_jax_dtype
        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.fftshift(v, axes=axes), _coerce(x),
                 _name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.ifftshift(v, axes=axes), _coerce(x),
                 _name="ifftshift")


def _res_axes(x, s, axes):
    nd = _coerce(x)._value.ndim
    if axes is None:
        axes = (tuple(range(nd)) if s is None
                else tuple(range(nd - len(s), nd)))
    res = tuple(a % nd for a in axes)
    if len(set(res)) != len(res):
        raise ValueError(
            f"duplicate transform axes {tuple(axes)} for a {nd}-D input")
    if s is not None and len(s) != len(res):
        raise ValueError(
            f"s has {len(s)} entries but {len(res)} transform axes")
    return res


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """n-D FFT of a signal Hermitian-symmetric along the LAST transform
    axis (real output). jnp has no hfftn; composed as fft over the
    leading axes then hfft over the last (distinct-axis transforms
    commute). Reference: python/paddle/fft.py hfftn."""
    axes = _res_axes(x, s, axes)

    def fn(v):
        out = v
        for i, ax in enumerate(axes[:-1]):
            n = s[i] if s is not None else None
            out = jnp.fft.fft(out, n=n, axis=ax, norm=_norm(norm))
        n_last = s[-1] if s is not None else None
        return jnp.fft.hfft(out, n=n_last, axis=axes[-1], norm=_norm(norm))
    return apply(fn, _coerce(x), _name="hfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn: ihfft along the last transform axis (real ->
    half-spectrum complex), then ifft along the leading axes."""
    axes = _res_axes(x, s, axes)

    def fn(v):
        n_last = s[-1] if s is not None else None
        out = jnp.fft.ihfft(v, n=n_last, axis=axes[-1], norm=_norm(norm))
        for i, ax in enumerate(axes[:-1]):
            n = s[i] if s is not None else None
            out = jnp.fft.ifft(out, n=n, axis=ax, norm=_norm(norm))
        return out
    return apply(fn, _coerce(x), _name="ihfftn")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)
