"""paddle.io — datasets and DataLoader.

Reference parity: python/paddle/io/ (Dataset, IterableDataset, DataLoader
with multiprocess workers, BatchSampler, DistributedBatchSampler, Subset,
random_split).

TPU-native worker story: with num_workers>0 and use_shared_memory=True the
loader forks numpy-only worker processes that collate batches and ship them
through the native shm ring channel (csrc/shm_channel.cc) — the same
transport design as the reference's shared-memory worker pool — while the
parent process alone owns JAX/XLA and does host→device placement. With
use_shared_memory=False (or if the native lib is unavailable) it falls back
to a background-thread prefetcher, which is the common jax practice when the
per-sample work is light.
"""
from __future__ import annotations

import bisect
import itertools
import math as pymath
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..tensor import Tensor, to_tensor
from ..framework.random import default_generator


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(
            len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[i - 1] if i > 0 else 0)
        return self.datasets[i][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        sizes = [int(pymath.floor(n * l)) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


# ----------------------------------------------------------------- samplers --
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement,
            p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Parity: python/paddle/io/dataloader/batch_sampler.py::
    DistributedBatchSampler — shards indices across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            try:
                from ..distributed import get_world_size, get_rank
                num_replicas = num_replicas or get_world_size()
                rank = rank if rank is not None else get_rank()
            except ImportError:
                num_replicas = num_replicas or 1
                rank = rank or 0
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(pymath.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# -------------------------------------------------------------- collation ---
def _np_tree_to_tensor(obj):
    """Convert a numpy-collated tree (from a worker process) to Tensors."""
    if isinstance(obj, np.ndarray):
        return to_tensor(obj)
    if isinstance(obj, tuple):
        return tuple(_np_tree_to_tensor(o) for o in obj)
    if isinstance(obj, list):
        return [_np_tree_to_tensor(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _np_tree_to_tensor(v) for k, v in obj.items()}
    return obj


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._value for b in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self._user_collate = collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            # Single-pass contract: expose worker info (id=0, num_workers=1)
            # so sharding IterableDatasets behave identically here and in
            # the multiprocess path (where each worker streams its shard).
            from . import _worker as _w
            prev = _w._WORKER_INFO
            _w._WORKER_INFO = _w.WorkerInfo(id=0, num_workers=1, seed=0,
                                            dataset=self.dataset)
            try:
                yield from self._iter_iterable_batches()
            finally:
                _w._WORKER_INFO = prev
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_iterable_batches(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_multiprocess(self):
        """Fork numpy-only workers feeding batches through the native shm
        channel; order is restored to match the single-process loader."""
        from ._worker import WorkerPool
        if self._iterable_mode:
            batch_indices = None
            bs, dl = self.batch_size, self.drop_last
        else:
            batch_indices = list(self.batch_sampler)
            bs, dl = 1, False
        pool = WorkerPool(
            self.dataset, batch_indices, self.num_workers,
            self._user_collate, self.worker_init_fn,
            seed=int(np.random.randint(0, 2 ** 31)),
            batch_size=bs, drop_last=dl)
        yield from pool

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        if self.use_shared_memory:
            from .._native import available as _native_ok
            if _native_ok():
                yield from self._iter_multiprocess()
                return
        # background-thread prefetch pipeline
        q: "queue.Queue" = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item


def get_worker_info():
    """Worker metadata inside DataLoader worker processes (else None)."""
    from ._worker import get_worker_info as _gwi
    return _gwi()


class SubsetRandomSampler(Sampler):
    """Parity: paddle.io.SubsetRandomSampler."""

    def __init__(self, indices):
        super().__init__(indices)
        self.indices = list(indices)

    def __iter__(self):
        import random as _random
        order = list(self.indices)
        _random.shuffle(order)
        return iter(order)

    def __len__(self):
        return len(self.indices)


def default_convert_fn(batch):
    """Parity: paddle.io.dataloader.collate.default_convert_fn — convert
    leaves to Tensors without stacking."""
    from ..tensor import Tensor
    import numpy as _np
    import jax.numpy as _jnp
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_convert_fn(b) for b in batch)
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    if isinstance(batch, Tensor):
        return batch
    if isinstance(batch, (_np.ndarray, _np.generic, int, float)):
        return Tensor(_jnp.asarray(batch))
    return batch


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Parity: paddle.io.multiprocess_reader (legacy reader composer).
    The native shm DataLoader worker pool is the fast path here; this
    shim interleaves the readers in-process (same yielded stream,
    deterministic round-robin instead of process-race order)."""
    def composed():
        iters = [r() for r in readers]
        alive = [True] * len(iters)
        while any(alive):
            for i, it in enumerate(iters):
                if not alive[i]:
                    continue
                try:
                    yield next(it)
                except StopIteration:
                    alive[i] = False
    return composed
