"""Declarative SLOs evaluated from the live metrics registry.

The autoscale view (serving/autoscale.py) exports raw pressure signals;
this module turns them into *objectives*: "99% of requests see first
token within 250ms", "99% of completions parse", per tenant tier. Each
:class:`SLOSpec` binds one objective to metric families the stack
already records, and :class:`SLOEngine.evaluate` keeps the error-budget
accounting the SRE playbook calls multi-window burn rates:

- budget = 1 - objective (the tolerated bad fraction).
- burn(W) = bad_fraction over window W / budget. burn == 1 means the
  budget is being spent exactly at the tolerated rate; burn == 10 means
  the budget for the whole window is gone in a tenth of it.
- a breach fires when BOTH the fast and the slow window burn above the
  threshold — the fast window makes the alert quick, the slow window
  keeps a transient blip from paging (and from flapping the controller
  that consumes these gauges, serving/controller.py).

Evaluation is cumulative-delta based: each tick diffs the underlying
counters/bucket counts against the previous tick and feeds the deltas
into rolling windows, so the engine works on top of the existing
monotonic families without private hooks. Latency objectives count an
observation as "good" when it lands in a histogram bucket at or below
the target — pick targets on bucket boundaries (DEFAULT_BUCKETS or a
custom `buckets=`) for exact accounting; an off-boundary target is
rounded conservatively (the straddling bucket counts as bad).

Exports (docs/OBSERVABILITY.md "SLOs & the control loop"):
``slo.burn_rate{slo,window}``, ``slo.target{slo}``,
``slo.breaches{slo}`` and, on each breach episode, one
``{"kind": "slo_breach"}`` JSONL record carrying the burn numbers AND
the offending spans from the flight recorder — the page includes its
own evidence.
"""
from __future__ import annotations

import bisect
import collections
import math
import time
from typing import Dict, List, Optional

from . import metrics as _obsm
from . import tracing as _obstr
from .runtime import export_record

__all__ = ["Ewma", "SLOSpec", "SLOEngine", "default_serving_slos"]


class Ewma:
    """Time-aware exponential moving average with a half-life.

    ``update(v, now)`` decays the held value toward ``v`` so that a
    constant input converges and a sample `half_life_s` old carries
    half the weight of a fresh one. Shared by the SLO engine's burn
    smoothing and the autoscale `desired_replicas` fix
    (serving/autoscale.py) so both flap-damp on the same clock.
    """

    def __init__(self, half_life_s: float = 30.0, now_fn=time.time):
        self.half_life_s = float(half_life_s)
        self._now = now_fn
        self._value: Optional[float] = None
        self._ts: Optional[float] = None

    def update(self, value: float, now: Optional[float] = None) -> float:
        t = self._now() if now is None else float(now)
        v = float(value)
        if self._value is None or self.half_life_s <= 0:
            self._value, self._ts = v, t
            return v
        prev = self._ts if self._ts is not None else t
        dt = max(t - prev, 0.0)
        alpha = 1.0 - math.pow(0.5, dt / self.half_life_s)
        self._value += alpha * (v - self._value)
        self._ts = t
        return self._value

    @property
    def value(self) -> Optional[float]:
        return self._value


class SLOSpec:
    """One declarative objective bound to registry families.

    kind="latency": `metric` names a Histogram; an observation is good
    when <= `target` (seconds, snapped to a bucket boundary).
    kind="ratio": `metric` names a Counter and `good_labels` selects
    the good series (e.g. status="ok"); every series matching `labels`
    counts toward the total — parse-valid rates, success rates.

    `labels` filters which series are in scope (per-tenant SLOs pass
    tier=...); `objective` is the required good fraction; `tier` is a
    display/routing label the controller uses to pick which tenant to
    protect.
    """

    def __init__(self, name: str, metric: str, target: float = 0.0,
                 kind: str = "latency", objective: float = 0.99,
                 labels: Optional[Dict[str, str]] = None,
                 good_labels: Optional[Dict[str, str]] = None,
                 tier: Optional[str] = None,
                 fallback_metrics: tuple = (),
                 evidence_span: str = "router.request",
                 description: str = ""):
        if kind not in ("latency", "ratio"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not (0.0 < objective < 1.0):
            raise ValueError("objective must be in (0, 1)")
        if kind == "ratio" and not good_labels:
            raise ValueError("ratio SLO needs good_labels")
        self.name = name
        self.metric = metric
        self.fallback_metrics = tuple(fallback_metrics)
        self.target = float(target)
        self.kind = kind
        self.objective = float(objective)
        self.labels = dict(labels or {})
        self.good_labels = dict(good_labels or {})
        self.tier = tier
        self.evidence_span = evidence_span
        self.description = description

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def as_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "kind": self.kind, "target": self.target,
                "objective": self.objective, "labels": self.labels,
                "good_labels": self.good_labels, "tier": self.tier}


def default_serving_slos(ttft_target_s: float = 0.25,
                         inter_token_target_s: float = 0.05,
                         objective: float = 0.95,
                         tier: Optional[str] = None) -> List[SLOSpec]:
    """The serving objectives every deployment starts from: TTFT,
    inter-token latency, and completion success rate (a parse-valid
    rate binds the same way: a ratio spec over its validity counter)."""
    tl = {"tier": tier} if tier else {}
    return [
        SLOSpec("ttft", "serving.router.ttft_seconds",
                target=ttft_target_s, objective=objective,
                labels=tl, tier=tier,
                fallback_metrics=("serving.ttft_seconds",),
                description="time to first token"),
        SLOSpec("inter_token", "serving.token_latency_seconds",
                target=inter_token_target_s, objective=objective,
                evidence_span="serve.request",
                description="decode inter-token latency"),
        SLOSpec("completion_ok", "serving.router.completed",
                kind="ratio", objective=objective,
                labels=tl, tier=tier, good_labels={"status": "ok"},
                description="requests finishing with status ok"),
    ]


class _Window:
    """Rolling (good, bad) totals over the last `horizon_s` seconds,
    fed with per-tick deltas."""

    __slots__ = ("horizon_s", "_buf", "_good", "_bad")

    def __init__(self, horizon_s: float):
        self.horizon_s = float(horizon_s)
        self._buf: collections.deque = collections.deque()
        self._good = 0.0
        self._bad = 0.0

    def add(self, ts: float, good: float, bad: float):
        if good or bad:
            self._buf.append((ts, good, bad))
            self._good += good
            self._bad += bad
        self._expire(ts)

    def _expire(self, now: float):
        cutoff = now - self.horizon_s
        buf = self._buf
        while buf and buf[0][0] < cutoff:
            _, g, b = buf.popleft()
            self._good -= g
            self._bad -= b

    def totals(self, now: float):
        self._expire(now)
        return self._good, self._bad


class _SpecState:
    __slots__ = ("cum_good", "cum_bad", "fast", "slow", "alerting",
                 "breaches")

    def __init__(self, fast_s: float, slow_s: float):
        self.cum_good: Optional[float] = None
        self.cum_bad: Optional[float] = None
        self.fast = _Window(fast_s)
        self.slow = _Window(slow_s)
        self.alerting = False    # breach episode in progress
        self.breaches = 0


def _labels_match(series_labels: dict, want: dict) -> bool:
    return all(series_labels.get(k) == v for k, v in want.items())


def _good_leq(series, target: float):
    """(good, total) observation counts for one histogram series: good
    = observations landing in buckets bounded at or below `target`."""
    with series._lock:
        buckets = series._buckets
        counts = list(series._counts)
        total = series._count
    k = bisect.bisect_left(buckets, target)
    good = sum(counts[:k])
    if k < len(buckets) and buckets[k] == target:
        good += counts[k]
    return good, total


class SLOEngine:
    """Continuous SLO evaluation over the process metric registry.

    ``evaluate()`` is the tick: diff the bound families, feed the
    fast/slow windows, export the ``slo.*`` gauges, and emit one
    evidence-carrying breach record per breach *episode* (re-armed when
    the fast window recovers below the threshold). Pure host-side
    bookkeeping — safe at controller-tick cadence. `now_fn` is
    injectable so tests drive a synthetic clock.
    """

    def __init__(self, specs: Optional[List[SLOSpec]] = None,
                 registry: Optional[object] = None,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 breach_burn: float = 1.0,
                 evidence_limit: int = 5,
                 now_fn=time.time):
        self.specs = list(specs if specs is not None
                          else default_serving_slos())
        self._reg = registry if registry is not None \
            else _obsm.get_registry()
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.breach_burn = float(breach_burn)
        self.evidence_limit = int(evidence_limit)
        self._now = now_fn
        self._state: Dict[str, _SpecState] = {
            s.name: _SpecState(self.fast_window_s, self.slow_window_s)
            for s in self.specs}
        self.last: Dict[str, dict] = {}

    # ------------------------------------------------------- accounting --
    def _metric_for(self, spec: SLOSpec):
        m = self._reg.get(spec.metric)
        for alt in spec.fallback_metrics:
            if m is not None and any(True for _ in m.samples()):
                break
            alt_m = self._reg.get(alt)
            if alt_m is not None:
                m = alt_m
        return m

    def _cumulative(self, spec: SLOSpec):
        """Cumulative (good, bad) event counts for one spec, summed
        over every in-scope labeled series."""
        m = self._metric_for(spec)
        if m is None:
            return 0.0, 0.0
        good = total = 0.0
        if spec.kind == "latency":
            for s in m.series():
                if not _labels_match(s._labels, spec.labels):
                    continue
                g, t = _good_leq(s, spec.target)
                good += g
                total += t
        else:
            want_good = dict(spec.labels)
            want_good.update(spec.good_labels)
            for s in m.series():
                if not _labels_match(s._labels, spec.labels):
                    continue
                total += s._value
                if _labels_match(s._labels, want_good):
                    good += s._value
        return good, max(total - good, 0.0)

    # ------------------------------------------------------------- tick --
    def evaluate(self, now: Optional[float] = None,
                 publish: bool = True) -> Dict[str, dict]:
        t = self._now() if now is None else float(now)
        out: Dict[str, dict] = {}
        for spec in self.specs:
            st = self._state[spec.name]
            good, bad = self._cumulative(spec)
            if st.cum_good is None or good < st.cum_good \
                    or bad < st.cum_bad:
                # first tick, or the registry was reset underneath us:
                # (re)baseline without crediting the jump to any window
                dg = db = 0.0
            else:
                dg = good - st.cum_good
                db = bad - st.cum_bad
            st.cum_good, st.cum_bad = good, bad
            st.fast.add(t, dg, db)
            st.slow.add(t, dg, db)
            status = self._status(spec, st, t)
            out[spec.name] = status
            if publish:
                self._publish(spec, st, status)
        self.last = out
        return out

    def _status(self, spec: SLOSpec, st: _SpecState, now: float) -> dict:
        burns = {}
        fracs = {}
        events = {}
        for wname, w in (("fast", st.fast), ("slow", st.slow)):
            g, b = w.totals(now)
            n = g + b
            frac = b / n if n else 0.0
            burns[wname] = frac / spec.budget
            fracs[wname] = frac
            events[wname] = (g, b)
        breach_now = (burns["fast"] >= self.breach_burn
                      and burns["slow"] >= self.breach_burn)
        new_episode = breach_now and not st.alerting
        if new_episode:
            st.breaches += 1
        st.alerting = breach_now
        return {"slo": spec.name, "kind": spec.kind,
                "target": spec.target, "objective": spec.objective,
                "tier": spec.tier, "burn": burns,
                "bad_fraction": fracs, "events": events,
                "breaching": breach_now, "new_breach": new_episode,
                "breaches": st.breaches}

    # ----------------------------------------------------------- export --
    def _publish(self, spec: SLOSpec, st: _SpecState, status: dict):
        tl = {"tier": spec.tier} if spec.tier else {}
        for wname, burn in status["burn"].items():
            self._reg.gauge("slo.burn_rate").set(
                burn, slo=spec.name, window=wname, **tl)
        self._reg.gauge("slo.target").set(spec.target, slo=spec.name)
        if status["new_breach"]:
            self._reg.counter("slo.breaches").inc(slo=spec.name, **tl)
            self._emit_breach(spec, status)

    def _emit_breach(self, spec: SLOSpec, status: dict):
        rec = {"kind": "slo_breach", "ts": round(time.time(), 6),
               "slo": spec.name, "target": spec.target,
               "objective": spec.objective, "tier": spec.tier,
               "burn_fast": round(status["burn"]["fast"], 4),
               "burn_slow": round(status["burn"]["slow"], 4),
               "window_fast_s": self.fast_window_s,
               "window_slow_s": self.slow_window_s,
               "events_fast": list(status["events"]["fast"]),
               "events_slow": list(status["events"]["slow"]),
               "evidence": self._evidence(spec),
               "exemplars": self._exemplars(spec)}
        export_record(rec)

    def _evidence(self, spec: SLOSpec) -> List[dict]:
        """The offending spans, straight off the flight-recorder ring:
        the breach record carries its own forensics."""
        out: List[dict] = []
        for sp in reversed(_obstr.flight_recorder().spans()):
            if len(out) >= self.evidence_limit:
                break
            if sp.get("name") != spec.evidence_span:
                continue
            labels = sp.get("labels", {})
            if not _labels_match(labels, spec.labels):
                continue
            if spec.kind == "latency" \
                    and sp.get("dur", 0.0) <= spec.target:
                continue
            if spec.kind == "ratio" and sp.get("status") in ("ok", None):
                continue
            out.append({"name": sp.get("name"), "trace": sp.get("trace"),
                        "span": sp.get("span"),
                        "dur": round(sp.get("dur", 0.0), 6),
                        "status": sp.get("status"), "labels": labels})
        return out

    def _exemplars(self, spec: SLOSpec) -> List[dict]:
        """Tail exemplars off the spec's bound histogram: the trace
        ids of its largest observations, so a burn page links straight
        to renderable traces (tools/trace_report.py --request)."""
        m = self._metric_for(spec)
        if m is None or not hasattr(m, "exemplars"):
            return []     # ratio specs bind counters: no exemplars
        try:
            ex = m.exemplars(**spec.labels) or m.exemplars()
        except Exception:
            return []
        return [{"value": round(v, 6), "trace": t} for v, t in ex]

    # ------------------------------------------------------ convenience --
    def burn(self, name: str, window: str = "fast") -> float:
        """Last evaluated burn rate (0.0 before the first tick)."""
        st = self.last.get(name)
        return st["burn"].get(window, 0.0) if st else 0.0

    def breaching(self, name: str) -> bool:
        st = self.last.get(name)
        return bool(st and st["breaching"])
