"""Pluggable exporters over MetricRegistry.collect().

Three sinks, one schema:
- JsonlExporter      — append-only JSONL file, one sample per line; the
                       shared schema of runtime telemetry, bench.py
                       timings and tools/metrics_report.py.
- PrometheusExporter — text-format snapshot (/metrics style) for pull
                       scrapers.
- TensorBoardExporter— scalars through utils/tbwriter.LogWriter (the
                       repo's zero-dep TensorBoard event writer).

Exporters PULL: recording a metric never touches a file descriptor; the
training/serving loop (or the auto-sink in __init__) decides when to
flush a snapshot.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from .metrics import MetricRegistry, Sample, get_registry

__all__ = ["JsonlExporter", "PrometheusExporter", "TensorBoardExporter"]


class JsonlExporter:
    """Append registry snapshots to a JSONL file.

    Line schema (one sample per line):
        {"ts": <unix s>, "step": <int|None>, "name": "train.step_time",
         "kind": "histogram", "labels": {...}, "value": <float>,
         ... histogram extras: count/sum/min/max/p50/p99}

    Size-based rotation: with ``max_bytes`` set (ctor arg, env default
    ``PADDLE_TPU_TELEMETRY_MAX_BYTES``; 0/unset disables), a file that
    reaches the bound is atomically renamed to ``<path>.1`` (one
    os.replace — a concurrent reader sees the old file or the new one,
    never a torn mix) and a fresh file continues at ``path``. Long
    serve runs stop growing the telemetry file unbounded; the readers
    (tools/{trace_report,metrics_report,autotune}.py) fold the rotated
    sibling back in. Rotation happens on whole-line boundaries only —
    every write here is a complete line.

    Fleet identity: every line additionally carries the process's
    ``rank`` / ``world_size`` / ``topology`` (``runtime.rank_identity``,
    sourced from the launcher env; override per-exporter with the
    ``identity`` ctor arg). Outside a launcher the identity is empty and
    the line schema is unchanged. Identity fields never overwrite keys a
    record already carries.
    """

    def __init__(self, path: str, registry: Optional[MetricRegistry] = None,
                 max_bytes: Optional[int] = None,
                 identity: Optional[dict] = None):
        self.path = path
        self._registry = registry or get_registry()
        if identity is None:
            from .runtime import export_identity
            identity = export_identity()
        self.identity = dict(identity)
        self._lock = threading.Lock()  # span ends vs step exports race
        if max_bytes is None:
            max_bytes = int(os.environ.get(
                "PADDLE_TPU_TELEMETRY_MAX_BYTES") or 0)
        self.max_bytes = max(int(max_bytes), 0)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def _maybe_rotate_locked(self):
        """Rotate when the live file crossed the bound (caller holds
        the lock). Best-effort: a failed rename keeps appending to the
        current file rather than dropping telemetry."""
        if not self.max_bytes or self._f is None:
            return
        try:
            if self._f.tell() < self.max_bytes:
                return
            f, self._f = self._f, None
            f.flush()
            f.close()
            try:
                os.replace(self.path, self.path + ".1")
            finally:
                self._f = open(self.path, "a", buffering=1)
        except OSError:
            if self._f is None:
                try:
                    self._f = open(self.path, "a", buffering=1)
                except OSError:
                    pass

    def export(self, step: Optional[int] = None, extra: Optional[dict] = None):
        ts = time.time()
        ident = self.identity
        lines = []
        for s in self._registry.collect():
            rec = {"ts": round(ts, 6), "step": step}
            if ident:
                rec.update(ident)
            rec.update(s.as_dict())
            if extra:
                rec.update(extra)
            lines.append(json.dumps(rec))
        with self._lock:
            if self._f is None:
                return
            self._f.write("\n".join(lines) + "\n" if lines else "")
            self._maybe_rotate_locked()

    def write_record(self, rec: dict):
        """Escape hatch for one-off records (bench.py run metadata,
        tracing span lines) that share the telemetry file but aren't
        registry series. Silent no-op once closed — late writers at
        interpreter teardown must not explode."""
        ident = self.identity
        if ident:
            rec = {**{k: v for k, v in ident.items() if k not in rec},
                   **rec}
        line = json.dumps(rec) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._maybe_rotate_locked()

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self):
        """Flush and close the file; idempotent (second close and any
        subsequent export/write_record are no-ops), so the atexit hook
        and an explicit configure(None) can both run."""
        with self._lock:
            f, self._f = self._f, None
        if f is None:
            return
        try:
            f.flush()
            f.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    return ("_" + s) if s and s[0].isdigit() else s


def _prom_escape(value) -> str:
    """Escape one label VALUE for the exposition format: backslash,
    double-quote, and newline (a raw newline inside the quotes tears the
    exposition line in half — topology/rank strings from env must not be
    able to corrupt a scrape)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join('%s="%s"' % (_prom_name(str(k)), _prom_escape(v))
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


class PrometheusExporter:
    """Render the registry in the Prometheus text exposition format.

    Under a launcher every sample line carries the process's fleet
    identity as `rank` / `world_size` / `topology` labels
    (`runtime.rank_identity`; override with ``const_labels``), so a
    fleet-wide scrape can tell the ranks apart. Label values are escaped
    per the exposition spec — a topology like ``data=4,model=2`` (or a
    value with quotes/newlines) renders as one well-formed line."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 const_labels: Optional[dict] = None):
        self._registry = registry or get_registry()
        if const_labels is None:
            from .runtime import export_identity
            const_labels = export_identity()
        self._const = {str(k): v for k, v in (const_labels or {}).items()}

    def _labels(self, labels: dict, extra: Optional[dict] = None) -> str:
        items = dict(self._const)
        items.update(labels)
        if extra:
            items.update(extra)
        return _prom_labels(items)

    def render(self) -> str:
        lines = []
        for m in self._registry.metrics():
            pname = _prom_name(m.name)
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if m.kind == "histogram":
                for s in m.series():
                    cum = 0
                    for b, c in zip(m.buckets, s._counts):
                        cum += c
                        lines.append(
                            f"{pname}_bucket"
                            f"{self._labels(s._labels, {'le': b})} {cum}")
                    lines.append(
                        f"{pname}_bucket"
                        f"{self._labels(s._labels, {'le': '+Inf'})} "
                        f"{s._count}")
                    lines.append(
                        f"{pname}_sum{self._labels(s._labels)} {s._sum}")
                    lines.append(
                        f"{pname}_count{self._labels(s._labels)} "
                        f"{s._count}")
            else:
                for s in m.series():
                    lines.append(
                        f"{pname}{self._labels(s._labels)} {s._value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render())
        os.replace(tmp, path)  # scrape never sees a torn file
        return path


class TensorBoardExporter:
    """Write registry scalars as TensorBoard events via the repo's
    zero-dependency utils/tbwriter.LogWriter. Histograms export their
    mean/p50/p99 as three scalar tags (TB's native histogram proto is
    out of scope for the wire writer)."""

    def __init__(self, logdir: str,
                 registry: Optional[MetricRegistry] = None):
        from ..utils.tbwriter import LogWriter
        self._registry = registry or get_registry()
        self._w = LogWriter(logdir=logdir)

    @staticmethod
    def _tag(s: Sample) -> str:
        if not s.labels:
            return s.name
        lab = ".".join(f"{k}={v}" for k, v in sorted(s.labels.items()))
        return f"{s.name}/{lab}"

    def export(self, step: int = 0):
        for s in self._registry.collect():
            tag = self._tag(s)
            if s.kind == "histogram":
                if not s.extra.get("count"):
                    continue
                self._w.add_scalar(tag + "/mean", s.value, step)
                self._w.add_scalar(tag + "/p50", s.extra["p50"], step)
                self._w.add_scalar(tag + "/p99", s.extra["p99"], step)
            else:
                self._w.add_scalar(tag, s.value, step)

    def flush(self):
        self._w.flush()

    def close(self):
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
