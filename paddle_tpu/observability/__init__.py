"""paddle_tpu.observability — always-on runtime telemetry.

The offline profiler (paddle_tpu.profiler, XPlane capture) answers "why
was this step slow"; this package answers "what is the system doing
RIGHT NOW and what did it do over the last million steps" — the metrics
layer every production trainer/server carries (tokens/s, MFU, comm
bytes, queue depths, latency quantiles, memory watermarks).

    import paddle_tpu.observability as obs

    obs.configure(jsonl_path="telemetry.jsonl")   # or env
    reqs = obs.counter("serving.requests")
    reqs.inc(reason="admitted")                   # labeled series
    obs.histogram("serving.ttft_seconds").observe(0.031)
    print(obs.PrometheusExporter().render())

    with obs.span("myapp.handle", request_id="r1") as sp:
        sp.event("admitted")                      # structured tracing:
        ...                                       # spans + flight
    obs.flight_dump(reason="debug")               # recorder (tracing.py)

    obs.enabled(False)    # every record becomes an early-return and
                          # jit_callback emits NOTHING when tracing

Instrumented out of the box: fleet.DistTrainStep / PipelineTrainStep
(step time, tokens/s, MFU, grad-norm, memory watermarks, per-axis
collective bytes), distributed.collective (per-op call/byte accounting),
inference.ContinuousBatchingPredictor (queue depth, page utilization,
TTFT / per-token latency, admissions/evictions/rejections), the Trainer
loop, bench.py, the elastic launcher (per-rank heartbeats), and the
fault-tolerance layer (robustness.* counters: anomalies skipped,
checkpoint retries/fallbacks, deadline evictions, shed requests,
watchdog trips, injected faults — docs/ROBUSTNESS.md). The fleet layer
(fleet.py) joins the per-rank files cross-rank: step skew, straggler
detection, comm-wait attribution (docs/OBSERVABILITY.md "Fleet view").
Metric catalog: docs/OBSERVABILITY.md.
"""
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, Sample, DEFAULT_BUCKETS,
    enabled, scoped, get_registry, counter, gauge, histogram,
)
from .exporters import (  # noqa: F401
    JsonlExporter, PrometheusExporter, TensorBoardExporter,
)
from .runtime import (  # noqa: F401
    jit_callback, device_memory_stats, configure, maybe_export,
    export_record, telemetry_path, RankHeartbeat, rank_identity,
    set_identity, export_identity,
)
from .slo import (  # noqa: F401
    Ewma, SLOSpec, SLOEngine, default_serving_slos,
)
from .fleet import (  # noqa: F401
    FleetAggregator, StragglerDetector, RankFileTailer,
)
from .tracing import (  # noqa: F401
    Span, TraceContext, NULL_SPAN, span, start_span, traced,
    current_span, FlightRecorder, flight_recorder, flight_dump,
    flight_dir, set_flight_dir, to_chrome_trace, write_chrome_trace,
)
from .critpath import (  # noqa: F401
    stage_decomposition, trace_tree,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "Sample",
    "DEFAULT_BUCKETS", "enabled", "scoped", "get_registry", "counter",
    "gauge", "histogram", "JsonlExporter", "PrometheusExporter",
    "TensorBoardExporter", "jit_callback", "device_memory_stats",
    "configure", "maybe_export", "export_record", "telemetry_path",
    "RankHeartbeat", "rank_identity", "set_identity", "export_identity",
    "Ewma", "SLOSpec", "SLOEngine", "default_serving_slos",
    "FleetAggregator",
    "StragglerDetector", "RankFileTailer",
    "Span", "TraceContext", "NULL_SPAN", "span", "start_span",
    "traced", "current_span", "FlightRecorder", "flight_recorder",
    "flight_dump", "flight_dir", "set_flight_dir", "to_chrome_trace",
    "write_chrome_trace", "stage_decomposition", "trace_tree",
]
