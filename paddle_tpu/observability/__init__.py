"""paddle_tpu.observability — always-on runtime telemetry.

The offline profiler (paddle_tpu.profiler, XPlane capture) answers "why
was this step slow"; this package answers "what is the system doing
RIGHT NOW and what did it do over the last million steps" — the metrics
layer every production trainer/server carries (tokens/s, MFU, comm
bytes, queue depths, latency quantiles, memory watermarks).

    import paddle_tpu.observability as obs

    obs.configure(jsonl_path="telemetry.jsonl")   # or env
    reqs = obs.counter("serving.requests")
    reqs.inc(reason="admitted")                   # labeled series
    obs.histogram("serving.ttft_seconds").observe(0.031)
    print(obs.PrometheusExporter().render())

    obs.enabled(False)    # every record becomes an early-return and
                          # jit_callback emits NOTHING when tracing

Instrumented out of the box: fleet.DistTrainStep / PipelineTrainStep
(step time, tokens/s, MFU, grad-norm, memory watermarks, per-axis
collective bytes), distributed.collective (per-op call/byte accounting),
inference.ContinuousBatchingPredictor (queue depth, page utilization,
TTFT / per-token latency, admissions/evictions/rejections), the Trainer
loop, bench.py, the elastic launcher (per-rank heartbeats), and the
fault-tolerance layer (robustness.* counters: anomalies skipped,
checkpoint retries/fallbacks, deadline evictions, shed requests,
watchdog trips, injected faults — docs/ROBUSTNESS.md). Metric catalog:
docs/OBSERVABILITY.md.
"""
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, Sample, DEFAULT_BUCKETS,
    enabled, scoped, get_registry, counter, gauge, histogram,
)
from .exporters import (  # noqa: F401
    JsonlExporter, PrometheusExporter, TensorBoardExporter,
)
from .runtime import (  # noqa: F401
    jit_callback, device_memory_stats, configure, maybe_export,
    telemetry_path, RankHeartbeat,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "Sample",
    "DEFAULT_BUCKETS", "enabled", "scoped", "get_registry", "counter",
    "gauge", "histogram", "JsonlExporter", "PrometheusExporter",
    "TensorBoardExporter", "jit_callback", "device_memory_stats",
    "configure", "maybe_export", "telemetry_path", "RankHeartbeat",
]
