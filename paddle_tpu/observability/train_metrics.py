"""Step telemetry shared by the compiled train steps and the Trainer.

One StepTelemetry object per step object records, per step and with no
forced device sync:

    train.step_time_seconds   histogram   wall time of one __call__*
    train.steps               counter
    train.tokens              counter     batch elements consumed
    train.tokens_per_sec      gauge
    train.mfu                 gauge       achieved / peak FLOP/s
    train.grad_norm           gauge       via jax.debug.callback (async)
    mem.bytes_in_use          gauge       device watermark (or live-array
    mem.peak_bytes_in_use     gauge       bytes on backends without
                                          allocator stats)
    comm.calls / comm.bytes   counter     labels op=..., axis=... —
                                          analytic accounting of the
                                          collectives XLA inserts for
                                          the declared shardings

*On an async-dispatch backend the __call__ wall time converges to the
true step time once the dispatch queue backpressures (steady state); the
first samples measure compile + dispatch.

MFU numerator: XLA's own cost model for the full step when the step
object exposes `cost_analysis` (hapi/flops.py's approach — exact for
what the program lowers to), computed ONCE per batch signature; falls
back to the 6·N·tokens analytic estimate.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import counter, enabled, gauge, histogram
from .runtime import device_memory_stats, jit_callback, maybe_export

__all__ = ["StepTelemetry", "peak_flops", "batch_tokens",
           "sharded_bytes"]


def sharded_bytes(leaves):
    """(global_bytes, per_replica_bytes) for a list of PLACED jax
    arrays: global is the full logical footprint, per_replica divides
    each leaf by the product of the mesh-axis sizes its NamedSharding
    spec names (the analytic per-device share — what ZeRO/TP sharding
    buys). Leaves without a NamedSharding count replicated."""
    import numpy as np
    tot = per = 0
    for v in leaves:
        shape = getattr(v, "shape", None)
        if shape is None:
            continue
        nb = int(np.prod(shape or (1,))) * np.dtype(v.dtype).itemsize
        tot += nb
        div = 1
        sh = getattr(v, "sharding", None)
        spec = getattr(sh, "spec", None)
        mesh = getattr(sh, "mesh", None)
        if spec is not None and mesh is not None:
            sizes = dict(getattr(mesh, "shape", {}) or {})
            for ax in spec:
                axes = ax if isinstance(ax, (tuple, list)) else (ax,)
                for a in axes:
                    if a is not None:
                        div *= int(sizes.get(a, 1))
        per += nb // max(div, 1)
    return tot, per


def peak_flops(dtype: str = "bfloat16") -> float:
    from ..trainer import device_peak_flops
    return device_peak_flops(dtype)


def batch_tokens(arrays) -> int:
    """Telemetry token count for a batch: B*T for integer id batches
    ([B, T] token ids), else the batch size. Shared by every step
    class so their tokens/s series agree."""
    import jax.numpy as jnp
    a = arrays[0]
    if a.ndim >= 2 and jnp.issubdtype(a.dtype, jnp.integer):
        return int(a.shape[0]) * int(a.shape[1])
    return int(a.shape[0]) if a.ndim else 1


class StepTelemetry:
    """Host-side recorder for one compiled train-step object."""

    def __init__(self, n_params: int, dtype: str = "float32",
                 n_devices: Optional[int] = None, prefix: str = "train",
                 comm_per_step: Optional[List[Tuple[str, str, int, int]]]
                 = None,
                 flops_fn: Optional[Callable[[], float]] = None,
                 mem_every: int = 1):
        self.prefix = prefix
        self.n_params = int(n_params)
        self.dtype = dtype
        if n_devices is None:
            import jax
            n_devices = jax.device_count()
        self.n_devices = int(n_devices)
        # (op, axis, calls, bytes) accounted once per step
        self.comm_per_step = list(comm_per_step or [])
        self._flops_fn = flops_fn
        self._flops_per_step: Optional[float] = None
        self._t0: Optional[float] = None
        self._step = 0
        self._mem_every = max(1, int(mem_every))

        self.h_step = histogram(f"{prefix}.step_time_seconds",
                                help="wall time per train step", unit="s")
        self.c_steps = counter(f"{prefix}.steps")
        self.c_tokens = counter(f"{prefix}.tokens")
        self.g_tps = gauge(f"{prefix}.tokens_per_sec")
        self.g_mfu = gauge(f"{prefix}.mfu")
        self.g_gnorm = gauge(f"{prefix}.grad_norm")
        self.g_mem = gauge("mem.bytes_in_use", unit="bytes")
        self.g_mem_peak = gauge("mem.peak_bytes_in_use", unit="bytes")
        self.c_comm_calls = counter("comm.calls")
        self.c_comm_bytes = counter("comm.bytes", unit="bytes")

    # -- traced side ----------------------------------------------------
    def grad_norm_callback(self, grads):
        """Call INSIDE the traced step with the grad list; emits an async
        host callback recording the global grad norm. No-op (nothing
        enters the jaxpr) when telemetry is disabled at trace time."""
        if not enabled():
            return
        import jax.numpy as jnp
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        jit_callback(lambda v: self.g_gnorm.set(float(v)), jnp.sqrt(sq))

    # -- host side ------------------------------------------------------
    def step_start(self):
        if not enabled():
            return
        self._t0 = time.perf_counter()

    def step_end(self, tokens: int, export_step: Optional[int] = None):
        """Record the step. `tokens` = batch elements consumed (0 skips
        throughput/MFU). Flushes the process JSONL sink if configured."""
        if not enabled() or self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self._step += 1
        self.h_step.observe(dt)
        self.c_steps.inc()
        if tokens:
            self.c_tokens.inc(tokens)
            tps = tokens / dt if dt > 0 else 0.0
            self.g_tps.set(tps)
            fps = self._flops_for(tokens)
            if fps:
                peak = peak_flops(self.dtype) * self.n_devices
                self.g_mfu.set((fps / dt) / peak if dt > 0 else 0.0)
        for op, axis, calls, nbytes in self.comm_per_step:
            self.c_comm_calls.inc(calls, op=op, axis=axis)
            self.c_comm_bytes.inc(nbytes, op=op, axis=axis)
        if (self._step % self._mem_every) == 0:
            mem = device_memory_stats()
            self.g_mem.set(mem["bytes_in_use"])
            self.g_mem_peak.set(mem["peak_bytes_in_use"])
        maybe_export(step=export_step if export_step is not None
                     else self._step)
        return dt

    def reset_flops(self, flops_fn: Optional[Callable[[], float]] = None):
        """Re-arm the (expensive) flops probe — call when the step's
        batch signature changes so MFU doesn't go stale at a new shape."""
        self._flops_fn = flops_fn if flops_fn is not None \
            else self._flops_fn
        self._flops_per_step = None

    def _flops_for(self, tokens: int) -> float:
        if self._flops_per_step is None and self._flops_fn is not None:
            fn, self._flops_fn = self._flops_fn, None  # one shot per arm
            try:
                self._flops_per_step = float(fn() or 0.0)
            except Exception:
                self._flops_per_step = 0.0
        if self._flops_per_step:
            return self._flops_per_step
        return 6.0 * self.n_params * tokens
