"""Metric primitives: Counter / Gauge / Histogram with labeled series.

Reference parity: paddle.profiler's statistic helpers plus Fleet's
performance logger (tokens/s, MFU, memory watermarks) — here unified as
one process-wide registry in the Prometheus data model (the de-facto
schema of production serving/training stacks; PAPERS.md serving systems
work treats these as first-class). Design constraints:

- Always-on and low-overhead: recording a sample is a dict lookup plus a
  float add under a lock; no device work, no sync, ever.
- Disable-able to literal no-ops: with ``enabled(False)`` every
  recording method returns before touching state, and the jit helper
  (`jit_callback`) emits NOTHING into traced programs — zero trace-time
  overhead, asserted by tests/test_observability.py.
- Exporters (exporters.py) pull from `collect()`; recording never
  blocks on I/O.
"""
from __future__ import annotations

import bisect
import contextlib
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "Sample",
    "enabled", "scoped", "get_registry", "counter", "gauge", "histogram",
    "DEFAULT_BUCKETS",
]

# Latency-shaped default buckets (seconds): 100us .. 60s.
DEFAULT_BUCKETS = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_RAW_CAP = 2048  # per-series reservoir for exact quantiles
_EXEMPLAR_CAP = 4  # per-series tail exemplars (largest observations)


class _State:
    enabled = os.environ.get("PADDLE_TPU_TELEMETRY", "1").lower() \
        not in ("0", "false", "off")


_state = _State()


def enabled(value: Optional[bool] = None) -> bool:
    """Get (no arg) or set the process-wide telemetry switch.

    ``enabled(False)`` turns every metric method into an early-return
    and makes `jit_callback` a no-op at TRACE time, so disabled programs
    carry no instrumentation at all."""
    if value is not None:
        _state.enabled = bool(value)
    return _state.enabled


@contextlib.contextmanager
def scoped(value: bool):
    """Temporarily set the telemetry switch (tests, overhead-sensitive
    sections)."""
    prev = _state.enabled
    _state.enabled = bool(value)
    try:
        yield
    finally:
        _state.enabled = prev


class Sample:
    """One exported data point: (name, kind, labels, value, extra)."""

    __slots__ = ("name", "kind", "labels", "value", "extra")

    def __init__(self, name, kind, labels, value, extra=None):
        self.name = name
        self.kind = kind
        self.labels = labels
        self.value = value
        self.extra = extra or {}

    def as_dict(self):
        d = {"name": self.name, "kind": self.kind,
             "labels": dict(self.labels), "value": self.value}
        d.update(self.extra)
        return d


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Base: a named family of labeled series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 registry: Optional["MetricRegistry"] = None):
        self.name = name
        self.help = help
        self.unit = unit
        self._lock = threading.Lock()
        self._series: Dict[Tuple, object] = {}
        if registry is not None:
            registry._register(self)

    def _new_series(self):
        raise NotImplementedError

    def labels(self, **labels):
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = self._new_series()
                s._labels = dict(labels)  # type: ignore[attr-defined]
            return s

    def _peek(self, labels):
        """Read-only lookup: never creates the series (reading a metric
        must not pollute exports with zero-valued series)."""
        with self._lock:
            return self._series.get(_label_key(labels))

    def series(self) -> List:
        with self._lock:
            return list(self._series.values())

    def reset(self):
        with self._lock:
            self._series.clear()

    def samples(self) -> Iterable[Sample]:
        raise NotImplementedError


class _CounterSeries:
    __slots__ = ("_value", "_labels", "_lock")

    def __init__(self):
        self._value = 0.0
        self._labels = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        if not _state.enabled:
            return
        with self._lock:
            self._value += float(amount)

    @property
    def value(self):
        return self._value


class Counter(_Metric):
    """Monotonically increasing count (calls, bytes, tokens, requests)."""

    kind = "counter"

    def _new_series(self):
        return _CounterSeries()

    def inc(self, amount: float = 1.0, **labels):
        if not _state.enabled:
            return
        self.labels(**labels).inc(amount)

    def value(self, **labels) -> float:
        s = self._peek(labels)
        return s.value if s is not None else 0.0

    def samples(self):
        for s in self.series():
            yield Sample(self.name, self.kind, s._labels, s._value)


class _GaugeSeries:
    __slots__ = ("_value", "_labels", "_lock")

    def __init__(self):
        self._value = 0.0
        self._labels = {}
        self._lock = threading.Lock()

    def set(self, value: float):
        if not _state.enabled:
            return
        self._value = float(value)  # single store: atomic under the GIL

    def inc(self, amount: float = 1.0):
        if not _state.enabled:
            return
        with self._lock:
            self._value += float(amount)

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self):
        return self._value


class Gauge(_Metric):
    """Point-in-time value (queue depth, memory bytes, MFU)."""

    kind = "gauge"

    def _new_series(self):
        return _GaugeSeries()

    def set(self, value: float, **labels):
        if not _state.enabled:
            return
        self.labels(**labels).set(value)

    def value(self, **labels) -> float:
        s = self._peek(labels)
        return s.value if s is not None else 0.0

    def samples(self):
        for s in self.series():
            yield Sample(self.name, self.kind, s._labels, s._value)


class _HistogramSeries:
    __slots__ = ("_buckets", "_counts", "_count", "_sum", "_min", "_max",
                 "_raw", "_exemplars", "_labels", "_lock")

    def __init__(self, buckets):
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # +inf bucket
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._raw: List[float] = []
        # tail exemplars: the _EXEMPLAR_CAP largest observations that
        # carried a trace id — the forensic bridge from an aggregate
        # upper quantile to the exact requests behind it
        self._exemplars: List[Tuple[float, str]] = []
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: Optional[str] = None):
        if not _state.enabled:
            return
        v = float(value)
        with self._lock:
            self._counts[bisect.bisect_left(self._buckets, v)] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            raw = self._raw
            if len(raw) >= _RAW_CAP:
                # decimate rather than slide: old+new samples both survive
                del raw[::2]
            raw.append(v)
            if exemplar is not None:
                ex = self._exemplars
                if len(ex) < _EXEMPLAR_CAP or v > ex[-1][0]:
                    ex.append((v, str(exemplar)))
                    ex.sort(key=lambda p: -p[0])
                    del ex[_EXEMPLAR_CAP:]

    def exemplars(self) -> List[Tuple[float, str]]:
        """(value, trace_id) pairs for the retained tail, largest
        first."""
        with self._lock:
            return list(self._exemplars)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    @property
    def mean(self):
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Exact quantile over the retained reservoir (all samples until
        _RAW_CAP, decimated beyond)."""
        if not self._raw:
            return 0.0
        xs = sorted(self._raw)
        if q <= 0:
            return xs[0]
        if q >= 1:
            return xs[-1]
        pos = q * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        frac = pos - lo
        return xs[lo] * (1 - frac) + xs[hi] * frac


class Histogram(_Metric):
    """Distribution of observations (step time, latency) with bucket
    counts for Prometheus export and a reservoir for exact quantiles."""

    kind = "histogram"

    def __init__(self, name, help="", unit="", registry=None, buckets=None):
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        super().__init__(name, help=help, unit=unit, registry=registry)

    def _new_series(self):
        return _HistogramSeries(self.buckets)

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels):
        if not _state.enabled:
            return
        self.labels(**labels).observe(value, exemplar=exemplar)

    def quantile(self, q: float, **labels) -> float:
        s = self._peek(labels)
        return s.quantile(q) if s is not None else 0.0

    def exemplars(self, **labels) -> List[Tuple[float, str]]:
        """Tail exemplars of one series (largest first); every series'
        pooled tail when no labels are given."""
        if labels:
            s = self._peek(labels)
            return s.exemplars() if s is not None else []
        out: List[Tuple[float, str]] = []
        for s in self.series():
            out.extend(s.exemplars())
        out.sort(key=lambda p: -p[0])
        return out[:_EXEMPLAR_CAP]

    def samples(self):
        for s in self.series():
            extra = {"count": s._count, "sum": s._sum,
                     "min": None if s._count == 0 else s._min,
                     "max": None if s._count == 0 else s._max,
                     "p50": s.quantile(0.5), "p99": s.quantile(0.99)}
            ex = s.exemplars()
            if ex:
                extra["exemplars"] = [
                    {"value": round(v, 6), "trace": t} for v, t in ex]
            yield Sample(self.name, self.kind, s._labels, s.mean,
                         extra=extra)


class MetricRegistry:
    """Process-wide metric collection: create-or-get by name, collect
    for exporters, reset between runs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, metric: _Metric):
        with self._lock:
            cur = self._metrics.get(metric.name)
            if cur is not None and type(cur) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{cur.kind}")
            self._metrics[metric.name] = metric

    def _get_or_make(self, cls, name, help, unit, **kw):
        # create-and-insert under ONE lock hold: two threads racing on
        # the first use must not each build a metric (the loser's would
        # be orphaned and its recordings invisible to collect())
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = cls(name, help=help, unit=unit, **kw)  # registry=None:
            self._metrics[name] = m                    # we insert here
            return m

    def counter(self, name, help="", unit="") -> Counter:
        return self._get_or_make(Counter, name, help, unit)

    def gauge(self, name, help="", unit="") -> Gauge:
        return self._get_or_make(Gauge, name, help, unit)

    def histogram(self, name, help="", unit="", buckets=None) -> Histogram:
        return self._get_or_make(Histogram, name, help, unit,
                                 buckets=buckets)

    def get(self, name) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def collect(self) -> List[Sample]:
        out: List[Sample] = []
        for m in self.metrics():
            out.extend(m.samples())
        return out

    def snapshot(self) -> Dict[str, List[dict]]:
        """{metric_name: [sample dicts]} — a JSON-able registry image."""
        out: Dict[str, List[dict]] = {}
        for s in self.collect():
            out.setdefault(s.name, []).append(s.as_dict())
        return out

    def reset(self):
        """Drop every series (metric FAMILIES stay registered so held
        references keep working and repopulate on next record)."""
        for m in self.metrics():
            m.reset()


_default_registry = MetricRegistry()


def get_registry() -> MetricRegistry:
    return _default_registry


def counter(name, help="", unit="") -> Counter:
    return _default_registry.counter(name, help=help, unit=unit)


def gauge(name, help="", unit="") -> Gauge:
    return _default_registry.gauge(name, help=help, unit=unit)


def histogram(name, help="", unit="", buckets=None) -> Histogram:
    return _default_registry.histogram(name, help=help, unit=unit,
                                       buckets=buckets)


def now() -> float:
    return time.time()
