"""Critical-path attribution over one request's span tree.

A disaggregated request leaves a *tree* of spans in one trace — the
router's ``router.request`` root, a prefill-side ``serve.request``, the
handoff events, a decode-side ``serve.request`` — and an aggregate p99
gauge cannot say which stage made it slow. This module folds that tree
into a telescoping stage decomposition: consecutive milestone
timestamps along the request's life, so the stage values sum EXACTLY to
the measured span window (TTFT up to the ``first_token`` milestone, E2E
up to ``finish``). The serving router exports the same decomposition
live as ``serve.request.stage.seconds{stage=...}`` histograms;
``tools/trace_report.py --request <trace_id>`` renders it offline from
the JSONL sink (it loads this file standalone — keep it stdlib-only,
no jax / paddle_tpu imports).

Stages, in path order (absent boundaries are skipped — a unified pool
has no handoff stages):

==================  ======================================================
``admission``       router submit -> replica chosen (``routed``)
``dispatch``        routed -> the replica serve loop saw the request
``queue``           replica intake -> prefill starts (batch admission)
``prefill``         prefill/chunked-ingest compute -> first token
``handoff_export``  prefill finished -> KV page span exported
``handoff_transfer``span exported -> decode replica begins the import
``handoff_import``  page-span import (verify + scatter) on decode
``decode_queue``    imported -> decode-side slot admission
``decode``          per-tick decode (spec draft/verify ticks included;
                    their counts ride ``aux``)
``flush``           last decode tick -> stream completion at the handle
==================  ======================================================
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["STAGES", "trace_tree", "stage_decomposition"]

STAGES = ("admission", "dispatch", "queue", "prefill", "handoff_export",
          "handoff_transfer", "handoff_import", "decode_queue",
          "decode", "flush")


def _ev_ts(span: dict, *names: str, last: bool = False) \
        -> Optional[float]:
    hit = None
    for ev in span.get("events") or ():
        if ev.get("name") in names and ev.get("ts") is not None:
            hit = float(ev["ts"])
            if not last:
                return hit
    return hit


def trace_tree(spans: List[dict], trace_id: Optional[str] = None) \
        -> dict:
    """Group `spans` (span dicts, ``as_dict`` schema) into one trace's
    tree: the root (``parent`` is None — ``router.request`` preferred,
    else the earliest), the trace's spans sorted by start, and any
    orphans (spans whose ``parent`` does not resolve inside the
    trace — a broken propagation chain)."""
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace") == trace_id]
    spans = sorted(spans, key=lambda s: float(s.get("start") or 0.0))
    ids = {s.get("span") for s in spans}
    roots = [s for s in spans if not s.get("parent")]
    root = None
    for s in roots:
        if s.get("name") == "router.request":
            root = s
            break
    if root is None and roots:
        root = roots[0]
    if root is None and spans:
        root = spans[0]
    orphans = [s for s in spans
               if s.get("parent") and s["parent"] not in ids]
    return {"root": root, "spans": spans, "orphans": orphans}


def _span_end(span: dict) -> Optional[float]:
    start = span.get("start")
    dur = span.get("dur")
    if start is None or dur is None:
        return None
    return float(start) + float(dur)


def stage_decomposition(spans: List[dict],
                        trace_id: Optional[str] = None) -> dict:
    """Fold one trace's spans into the telescoping stage table.

    Returns ``{"trace", "stages": [(stage, seconds), ...], "ttft",
    "e2e", "aux"}``. ``stages`` telescopes: each value is the gap to
    the previous milestone (clamped monotonic), so
    ``sum(stages) == e2e`` and the prefix up to the ``prefill`` stage
    sums to ``ttft`` — by construction, not by luck. ``ttft``/``e2e``
    are None/0 when the trace never reached the milestone."""
    tree = trace_tree(spans, trace_id=trace_id)
    root = tree["root"]
    if root is None:
        return {"trace": trace_id, "stages": [], "ttft": None,
                "e2e": 0.0, "aux": {"orphans": 0}}
    t0 = float(root.get("start") or 0.0)
    sreqs = [s for s in tree["spans"] if s.get("name") == "serve.request"]
    first_sreq = sreqs[0] if sreqs else None
    is_router_root = root.get("name") == "router.request"
    if not is_router_root and first_sreq is None \
            and root.get("name") == "serve.request":
        first_sreq = root

    # ---- milestone timestamps (None = boundary never crossed) --------
    m: List[Tuple[str, Optional[float]]] = []
    if is_router_root:
        m.append(("admission", _ev_ts(root, "routed")))
        m.append(("dispatch", float(first_sreq["start"])
                  if first_sreq else None))
    if first_sreq is not None:
        m.append(("queue", _ev_ts(first_sreq, "prefill", "admitted")))
    # first_token on the root (the handle's stream clock — what the
    # router's TTFT histogram measures) falls back to the serve loop's
    ft = _ev_ts(root, "first_token")
    if ft is None and first_sreq is not None:
        ft = _ev_ts(first_sreq, "first_token")
    m.append(("prefill", ft))
    if is_router_root:
        m.append(("handoff_export", _ev_ts(root, "handoff")))
        m.append(("handoff_transfer",
                  _ev_ts(root, "handoff_import_start")))
        m.append(("handoff_import", _ev_ts(root, "handoff_imported",
                                           "handoff_import_failed")))
        post = [s for s in sreqs[1:]]
        if post:
            m.append(("decode_queue", _ev_ts(post[0], "admitted")))
    dec_fin = None
    for s in reversed(sreqs):
        dec_fin = _ev_ts(s, "finish", last=True)
        if dec_fin is not None:
            break
    m.append(("decode", dec_fin))
    end = _ev_ts(root, "finish", last=True) or _span_end(root)
    m.append(("flush", end))

    stages: List[Tuple[str, float]] = []
    ttft = None
    prev = t0
    for stage, ts in m:
        if ts is None:
            continue
        ts = max(float(ts), prev)      # keep the telescoping exact
        stages.append((stage, ts - prev))
        prev = ts
        if stage == "prefill":
            ttft = prev - t0
    e2e = prev - t0

    spec_ticks = spec_accepted = tokens = 0
    for s in sreqs:
        for ev in s.get("events") or ():
            n = ev.get("name")
            if n == "spec":
                spec_ticks += 1
                spec_accepted += int(ev.get("accepted") or 0)
            elif n == "token":
                tokens += 1
    return {"trace": root.get("trace"), "stages": stages, "ttft": ttft,
            "e2e": e2e,
            "aux": {"orphans": len(tree["orphans"]),
                    "spans": len(tree["spans"]), "tokens": tokens,
                    "spec_ticks": spec_ticks,
                    "spec_accepted": spec_accepted,
                    "status": root.get("status")}}
