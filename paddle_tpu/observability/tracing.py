"""Structured tracing + the flight recorder.

The metrics layer (metrics.py) answers *what is the system doing*;
this module answers *what happened to THIS request / THIS step / THIS
crashed run*. Two pieces:

- **Spans.** A span is one timed operation with identity: trace_id
  (shared by every span of one request/step/run), span_id, parent span,
  labels, and timestamped events. Spans nest through a thread-local
  context stack (``with span("train.dispatch"): ...``) or explicitly
  (``start_span(..., parent=...)``) for lifecycles that interleave on
  one thread, like serving requests in the continuous-batching loop.
  Finished spans export through the process JSONL sink (runtime.py) as
  ``{"kind": "span", ...}`` lines — same file as the metric samples —
  and convert to Chrome-trace/Perfetto JSON (:func:`to_chrome_trace`).

- **Flight recorder.** Every finished span also lands in a bounded
  in-memory ring; still-open spans are tracked separately. On crash
  paths — the uncaught-exception hook installed here, the Trainer's
  SIGTERM/SIGINT chain, ``AnomalousTrainingError``,
  ``DecodeWedgedError``/decode-watchdog, bench backend-init wedge —
  :func:`flight_dump` writes the ring, the open spans (the forensic
  gold: *which phase was in progress*), armed-fault events, and a
  registry snapshot to ``flight_<pid>.json``. BENCH_r01–r05 all died as
  opaque ``rc=3`` wedges with zero forensic output; this is the fix.

Cost contract (same bar as the metrics layer, asserted by
tests/test_tracing.py): spans are pure host-side bookkeeping — they add
ZERO operations to jitted programs — and with ``enabled(False)`` every
tracing entry point returns the shared no-op span after one flag check.
"""
from __future__ import annotations

import collections
import json
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

from .metrics import enabled, get_registry

__all__ = [
    "Span", "TraceContext", "NULL_SPAN", "span", "start_span", "traced",
    "current_span", "FlightRecorder", "flight_recorder", "flight_dump",
    "flight_dir", "set_flight_dir", "to_chrome_trace",
    "write_chrome_trace",
]

# own RNG: span ids must not perturb (or be perturbed by) user-level
# random seeding (paddle.seed seeds the global streams)
_rand = random.Random(int.from_bytes(os.urandom(8), "big"))
_rand_lock = threading.Lock()

_MAX_EVENTS = 256          # per-span event cap (decode ticks, retries)
_DEFAULT_CAPACITY = 2048   # flight ring length (finished spans)

_UNSET = object()


def _new_id() -> str:
    with _rand_lock:
        return f"{_rand.getrandbits(64):016x}"


class _TLS(threading.local):
    def __init__(self):
        self.stack: List["Span"] = []


_tls = _TLS()


def current_span() -> Optional["Span"]:
    """The innermost active context-manager span on this thread (or
    None). Explicit `start_span(...)` spans do NOT enter the stack —
    they are addressed by reference."""
    s = _tls.stack
    return s[-1] if s else None


class _NullSpan:
    """Shared do-nothing span: every tracing entry point returns this
    when telemetry is disabled, so instrumented code needs no
    conditionals and the disabled cost is one flag check + method
    dispatch."""

    __slots__ = ()
    name = ""
    trace_id = span_id = parent_id = None
    recording = False
    ended = True

    def event(self, name, **attrs):
        return self

    def set_label(self, **labels):
        return self

    def end(self, status=None, **labels):
        return self

    def context(self, **baggage):
        return None   # disabled: nothing to propagate

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False


NULL_SPAN = _NullSpan()


class TraceContext:
    """Serializable trace identity for crossing a boundary the span
    object itself cannot cross (another thread's serve loop, a queue, a
    KV page-span handoff record, another process).

    A context names a parent: a span created with ``parent=ctx`` joins
    ``ctx.trace_id`` with ``parent_id = ctx.span_id``, so the receiving
    side's spans chain under the sender's without sharing memory.
    ``baggage`` carries request-scoped attribution (tenant/tier/role)
    that boundaries may stamp onto their own spans' labels.

    The dict form (:meth:`to_dict`/:meth:`from_dict`) is plain JSON
    and is what rides records like the serving handoff payload."""

    __slots__ = ("trace_id", "span_id", "baggage")

    def __init__(self, trace_id: str, span_id: str,
                 baggage: Optional[Dict] = None):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.baggage = dict(baggage) if baggage else {}

    def to_dict(self) -> dict:
        d = {"trace": self.trace_id, "span": self.span_id}
        if self.baggage:
            d["baggage"] = dict(self.baggage)
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "Optional[TraceContext]":
        """None-tolerant: a record without a context decodes to None
        (the receiver then falls back to its local root)."""
        if not d or "trace" not in d or "span" not in d:
            return None
        return cls(d["trace"], d["span"], d.get("baggage"))

    def __repr__(self):
        return (f"TraceContext(trace={self.trace_id!r}, "
                f"span={self.span_id!r}, baggage={self.baggage!r})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id
                and other.baggage == self.baggage)


class Span:
    """One timed operation. Create via :func:`span` (context manager,
    joins the thread-local stack) or :func:`start_span` (explicit
    lifetime; call ``.end()``)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "labels",
                 "events", "status", "start", "dur", "dropped_events",
                 "_t0", "_ended", "_on_stack")

    recording = True

    def __init__(self, name: str,
                 parent: "Optional[Span | TraceContext]" = None,
                 trace_id: Optional[str] = None,
                 labels: Optional[Dict] = None):
        self.name = name
        # `parent` may be a live Span (same thread) or a TraceContext
        # carried across a boundary — either way the child joins the
        # parent's trace with a resolvable parent_id
        self.parent_id = parent.span_id if parent else None
        self.trace_id = trace_id or (parent.trace_id if parent
                                     else _new_id())
        self.span_id = _new_id()
        self.labels = dict(labels) if labels else {}
        self.events: List[dict] = []
        self.status = "ok"
        self.dropped_events = 0
        self.start = time.time()
        self._t0 = time.perf_counter()
        self._ended = False
        self._on_stack = False
        _ensure_excepthook()
        _recorder._open_span(self)

    # ------------------------------------------------------------------
    @property
    def ended(self) -> bool:
        return self._ended

    def _now(self) -> float:
        # wall-clock anchored, monotonic-advanced: event timestamps sort
        # correctly within a span even across NTP steps
        return self.start + (time.perf_counter() - self._t0)

    def event(self, name: str, **attrs):
        """Append a timestamped event; capped at _MAX_EVENTS per span
        (decode ticks on a long generation), overflow counted."""
        if self._ended:
            return self
        if len(self.events) >= _MAX_EVENTS:
            self.dropped_events += 1
            return self
        ev = {"ts": round(self._now(), 6), "name": name}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)
        return self

    def set_label(self, **labels):
        self.labels.update(labels)
        return self

    def context(self, **baggage) -> "TraceContext":
        """Mint a :class:`TraceContext` naming this span as the parent
        for spans created across a boundary (thread, queue, handoff
        record, process)."""
        return TraceContext(self.trace_id, self.span_id, baggage)

    def end(self, status: Optional[str] = None, **labels):
        """Finish the span (idempotent): records duration, moves it from
        the open set into the flight ring, exports it through the
        process JSONL sink if one is configured."""
        if self._ended:
            return self
        self._ended = True
        self.dur = time.perf_counter() - self._t0
        if status is not None:
            self.status = status
        if labels:
            self.labels.update(labels)
        _recorder._close_span(self)
        if enabled():
            from .runtime import export_record
            export_record(self.as_dict())
        return self

    def as_dict(self, open: bool = False) -> dict:
        d = {"ts": round(time.time(), 6), "kind": "span",
             "name": self.name, "trace": self.trace_id,
             "span": self.span_id, "parent": self.parent_id,
             "start": round(self.start, 6),
             "dur": round(self.dur if self._ended
                          else time.perf_counter() - self._t0, 6),
             "labels": dict(self.labels), "events": list(self.events),
             "status": self.status}
        if open:
            d["open"] = True
        if self.dropped_events:
            d["dropped_events"] = self.dropped_events
        return d

    # ------------------------------------------------- context manager --
    def __enter__(self):
        _tls.stack.append(self)
        self._on_stack = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._on_stack:
            self._on_stack = False
            stack = _tls.stack
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:       # mismatched exits: still unwind
                stack.remove(self)
        if exc_type is not None and self.status == "ok":
            self.event("exception", type=exc_type.__name__,
                       message=str(exc)[:200])
            self.end(status=f"error:{exc_type.__name__}")
        else:
            self.end()
        return False


def span(name: str, parent=_UNSET, trace_id: Optional[str] = None,
         **labels) -> "Span | _NullSpan":
    """Context-manager span: nests under the current thread-local span
    unless an explicit ``parent`` (a Span, a :class:`TraceContext`
    carried across a boundary, or ``parent=None`` for a root) is
    given. No-op when telemetry is disabled."""
    if not enabled():
        return NULL_SPAN
    if parent is _UNSET:
        parent = current_span()
    elif isinstance(parent, _NullSpan):
        parent = None
    return Span(name, parent=parent, trace_id=trace_id, labels=labels)


def start_span(name: str, parent=_UNSET, trace_id: Optional[str] = None,
               **labels) -> "Span | _NullSpan":
    """Explicit-lifetime span (caller must ``.end()``): for lifecycles
    that interleave on one thread, e.g. one span per serving request
    while the decode loop round-robins the batch."""
    return span(name, parent=parent, trace_id=trace_id, **labels)


def traced(name=None, **labels):
    """Decorator: run the function inside a span (named after the
    function unless given). ``@traced`` and ``@traced("x", k=v)`` both
    work; disabled telemetry bypasses straight to the function."""
    import functools

    def deco(fn):
        sname = name if isinstance(name, str) and name else fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not enabled():
                return fn(*args, **kwargs)
            with span(sname, **labels):
                return fn(*args, **kwargs)
        return wrapper

    if callable(name):              # bare @traced
        fn, name = name, None
        return deco(fn)
    return deco


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Bounded ring of finished spans + the set of still-open ones,
    dumpable to JSON on crash paths. One process-wide instance
    (:func:`flight_recorder`); capacity via constructor or
    ``PADDLE_TPU_FLIGHT_CAPACITY``."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._open: Dict[str, Span] = {}
        self.last_dump: Optional[str] = None

    # ------------------------------------------------- span lifecycle --
    def _open_span(self, s: Span):
        with self._lock:
            if len(self._open) >= 4 * self.capacity:
                # leak guard: a caller that never ends its spans must
                # not grow the open set without bound
                self._open.pop(next(iter(self._open)))
            self._open[s.span_id] = s

    def _close_span(self, s: Span):
        with self._lock:
            self._open.pop(s.span_id, None)
            self._ring.append(s.as_dict())

    # ------------------------------------------------------- inspection --
    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def open_spans(self) -> List[dict]:
        with self._lock:
            live = list(self._open.values())
        return [s.as_dict(open=True) for s in live]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._open.clear()

    # ------------------------------------------------------------ dump --
    def dump(self, path: Optional[str] = None, reason: str = "",
             extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the flight file and return its path. Skips (returns
        None) when there is nothing recorded and not ``force`` — crash
        hooks can call this unconditionally. NEVER raises: this runs on
        paths where a second failure would mask the first."""
        try:
            finished, open_ = self.spans(), self.open_spans()
            if not finished and not open_ and not force:
                return None
            payload = {"ts": round(time.time(), 6), "pid": os.getpid(),
                       "reason": reason, "capacity": self.capacity,
                       "spans": finished, "open_spans": open_}
            try:  # armed-fault forensics (which injected fault fired)
                from ..framework import faults as _faults
                payload["fault_events"] = _faults.events()
            except Exception:
                pass
            try:
                payload["metrics"] = get_registry().snapshot()
            except Exception:
                pass
            if extra:
                payload["extra"] = extra
            if path is None:
                path = os.path.join(flight_dir(),
                                    f"flight_{os.getpid()}.json")
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)   # readers never see a torn dump
            self.last_dump = path
            return path
        except Exception:
            return None


_recorder = FlightRecorder(
    capacity=int(os.environ.get("PADDLE_TPU_FLIGHT_CAPACITY",
                                _DEFAULT_CAPACITY)))


def flight_recorder() -> FlightRecorder:
    return _recorder


def flight_dump(path: Optional[str] = None, reason: str = "",
                extra: Optional[dict] = None,
                force: bool = False) -> Optional[str]:
    """Dump the process flight recorder (see FlightRecorder.dump)."""
    return _recorder.dump(path=path, reason=reason, extra=extra,
                          force=force)


_flight_dir: Optional[str] = None


def set_flight_dir(path: Optional[str]):
    """Where crash dumps land when no explicit path is given."""
    global _flight_dir
    _flight_dir = path


def flight_dir() -> str:
    """Dump directory resolution: set_flight_dir > env
    PADDLE_TPU_FLIGHT_DIR > the telemetry sink's directory >
    ``output/`` under the cwd. The final fallback is deliberately NOT
    the cwd itself — crash dumps from ad-hoc runs used to litter the
    repository root; they now land in an output directory (created on
    demand by dump())."""
    if _flight_dir:
        return _flight_dir
    env = os.environ.get("PADDLE_TPU_FLIGHT_DIR")
    if env:
        return env
    from .runtime import telemetry_path
    tp = telemetry_path()
    if tp:
        return os.path.dirname(os.path.abspath(tp))
    return os.path.join(os.getcwd(), "output")


# ------------------------------------------------- uncaught-exception hook --
_hook_lock = threading.Lock()
_hook_installed = False


def _ensure_excepthook():
    """Chain a crash dump into sys.excepthook, once, lazily (first real
    span): an uncaught exception leaves flight_<pid>.json naming what
    was in flight, then the previous hook (traceback printing) runs."""
    global _hook_installed
    if _hook_installed:
        return
    with _hook_lock:
        if _hook_installed:
            return
        _hook_installed = True
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                _recorder.dump(reason=f"uncaught:{exc_type.__name__}")
            except Exception:
                pass
            prev(exc_type, exc, tb)

        sys.excepthook = hook


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------
def to_chrome_trace(spans: List[dict]) -> dict:
    """Span dicts -> Chrome-trace JSON (chrome://tracing / Perfetto):
    one complete ("X") event per span, one instant ("i") event per span
    event. Spans of one trace share a tid so a request/step reads as one
    row."""
    pid = os.getpid()
    tids: Dict[str, int] = {}
    out = []
    for s in spans:
        key = s.get("trace") or s.get("span") or s.get("name", "?")
        tid = tids.setdefault(key, len(tids) + 1)
        args = dict(s.get("labels") or {})
        args["status"] = s.get("status", "ok")
        args["trace"] = s.get("trace")
        if s.get("open"):
            args["open"] = True
        out.append({"ph": "X", "cat": "span", "name": s.get("name", "?"),
                    "ts": float(s.get("start", 0.0)) * 1e6,
                    "dur": max(float(s.get("dur") or 0.0), 0.0) * 1e6,
                    "pid": pid, "tid": tid, "args": args})
        for e in s.get("events") or []:
            out.append({"ph": "i", "s": "t",
                        "name": f"{s.get('name', '?')}:{e.get('name')}",
                        "ts": float(e.get("ts", 0.0)) * 1e6,
                        "pid": pid, "tid": tid,
                        "args": {k: v for k, v in e.items()
                                 if k not in ("ts", "name")}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Optional[List[dict]] = None) \
        -> str:
    """Write Chrome-trace JSON for `spans` (default: the flight ring,
    finished + open)."""
    if spans is None:
        spans = _recorder.spans() + _recorder.open_spans()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    return path
