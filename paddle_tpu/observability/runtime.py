"""Runtime glue: jit-safe recording, device memory watermarks, the
process auto-sink, and per-rank heartbeats.

The contract with jitted code: metrics NEVER force a device sync. A
traced value reaches the registry through `jax.debug.callback` (async,
host-side, ordered by the runtime) and ONLY when telemetry is enabled at
trace time — `jit_callback` with telemetry disabled emits nothing into
the jaxpr, so the disabled mode costs literally zero inside compiled
programs (asserted by tests/test_observability.py).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from .metrics import enabled, get_registry

__all__ = ["jit_callback", "device_memory_stats", "configure",
           "maybe_export", "export_record", "telemetry_path",
           "RankHeartbeat", "rank_identity", "set_identity",
           "export_identity"]


# ------------------------------------------------------- rank identity ------
# Fleet observability (docs/OBSERVABILITY.md "Fleet view") joins telemetry
# across ranks, which only works if every exported line says which rank
# wrote it. The identity is sourced once from the launcher env
# (PADDLE_TRAINER_ID/RANK, PADDLE_TRAINERS_NUM/WORLD_SIZE,
# PADDLE_TPU_TOPOLOGY) and merged into every JSONL record by the sink;
# single-process runs (no rank env) keep their line schema unchanged.
_identity: Optional[dict] = None


def _env_identity() -> dict:
    rank = os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK"))
    if rank is None:
        return {}
    out = {"rank": int(rank)}
    ws = os.environ.get("PADDLE_TRAINERS_NUM",
                        os.environ.get("WORLD_SIZE"))
    if ws is not None:
        out["world_size"] = int(ws)
    topo = os.environ.get("PADDLE_TPU_TOPOLOGY")
    if topo:
        out["topology"] = topo
    return out


def rank_identity() -> dict:
    """This process's fleet identity: `{"rank", "world_size",
    "topology"}` (any subset; `{}` outside a launcher). Cached on first
    read; `set_identity` overrides."""
    global _identity
    if _identity is None:
        try:
            _identity = _env_identity()
        except (TypeError, ValueError):
            _identity = {}
    return dict(_identity)


def export_identity() -> dict:
    """The identity exporters stamp on every record: the full
    rank_identity() under a launcher, `{}` otherwise. Gated on a
    ``rank`` being present so a process-local topology stamp
    (`HybridTrainStep` in a single-process run) cannot change the
    single-process line schema — outside a launcher, telemetry lines
    and Prometheus labels stay exactly as they always were."""
    ident = rank_identity()
    return ident if "rank" in ident else {}


def set_identity(rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 topology: Optional[str] = None) -> dict:
    """Override/extend the cached identity (the hybrid engine names its
    mesh topology here so rank files record the layout they ran under).
    Only the given fields change; returns the resulting identity. An
    already-attached process sink picks the change up immediately."""
    global _identity
    ident = rank_identity()
    if rank is not None:
        ident["rank"] = int(rank)
    if world_size is not None:
        ident["world_size"] = int(world_size)
    if topology is not None:
        ident["topology"] = str(topology)
    _identity = ident
    with _Sink.lock:
        if _sink.exporter is not None:
            _sink.exporter.identity = export_identity()
    return dict(ident)


def jit_callback(fn: Callable, *traced_args):
    """Record traced values host-side from inside a jitted function.

    `fn(*host_values)` runs on the host with numpy arrays once the
    device values materialize (jax.debug.callback: async, no sync).
    When telemetry is disabled AT TRACE TIME this is a literal no-op —
    nothing enters the program. Callers re-jit (new step object / new
    signature) to pick up a toggled switch; already-compiled programs
    keep the behavior they were traced with.
    """
    if not enabled():
        return
    import jax

    def _guarded(*vals):
        if not enabled():  # runtime toggle after trace: drop silently
            return
        try:
            fn(*vals)
        except Exception:
            pass  # telemetry must never kill a training step

    jax.debug.callback(_guarded, *traced_args)


def device_memory_stats() -> dict:
    """Best-effort device memory watermark, no sync.

    On real accelerators `Device.memory_stats()` reports allocator
    watermarks; the CPU backend returns None, so we fall back to the
    bytes of every live jax.Array (an upper bound that tracks leaks the
    same way).  Returns {"bytes_in_use", "peak_bytes_in_use", "source"}.
    """
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats:
        return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0))),
                "source": "memory_stats"}
    try:
        live = sum(a.nbytes for a in jax.live_arrays())
    except Exception:
        live = 0
    return {"bytes_in_use": int(live), "peak_bytes_in_use": int(live),
            "source": "live_arrays"}


# --------------------------------------------------------------- sink ------
class _Sink:
    lock = threading.Lock()
    exporter = None          # JsonlExporter
    every = 1                # export every N maybe_export calls
    _calls = 0


_sink = _Sink()
_atexit_registered = False


def _close_sink_at_exit():
    """Interpreter-teardown flush: the last partial snapshot (or span)
    written just before exit must reach disk even when the owner never
    called configure(None). JsonlExporter.close() is idempotent, so a
    sink closed earlier by hand is a no-op here."""
    with _Sink.lock:
        exp, _sink.exporter = _sink.exporter, None
    if exp is not None:
        exp.close()


def configure(jsonl_path: Optional[str] = None, every: int = 1):
    """Attach (or detach, with None) the process JSONL telemetry sink.

    Instrumented hot paths call `maybe_export(step=...)` once per step;
    with a sink configured that appends one registry snapshot every
    `every` calls. Env default: PADDLE_TPU_TELEMETRY_JSONL. The sink is
    flushed and closed at interpreter exit (atexit) if still attached.
    """
    global _atexit_registered
    from .exporters import JsonlExporter
    with _Sink.lock:
        if _sink.exporter is not None:
            _sink.exporter.close()
            _sink.exporter = None
        if jsonl_path:
            _sink.exporter = JsonlExporter(jsonl_path)
        _sink.every = max(1, int(every))
        _sink._calls = 0
    if not _atexit_registered:
        _atexit_registered = True
        import atexit
        atexit.register(_close_sink_at_exit)


def telemetry_path() -> Optional[str]:
    return _sink.exporter.path if _sink.exporter is not None else None


_env_checked = False


def _ensure_env_sink():
    global _env_checked
    if _env_checked or _sink.exporter is not None:
        return
    _env_checked = True
    path = os.environ.get("PADDLE_TPU_TELEMETRY_JSONL")
    if path:
        configure(path)


def maybe_export(step: Optional[int] = None):
    """Flush a registry snapshot to the configured JSONL sink (no-op
    when telemetry is disabled or no sink is configured)."""
    if not enabled():
        return
    _ensure_env_sink()
    with _Sink.lock:
        exp = _sink.exporter
        if exp is None:
            return
        _sink._calls += 1
        if (_sink._calls % _sink.every) != 0:
            return
        exp.export(step=step)


def export_record(rec: dict):
    """Write one raw record (span lines, one-off run metadata) through
    the process JSONL sink; silent no-op without a sink. This is how
    tracing.Span.end lands `{"kind": "span"}` lines in the same file as
    the metric samples."""
    if not enabled():
        return
    _ensure_env_sink()
    with _Sink.lock:
        exp = _sink.exporter
        if exp is None:
            return
        exp.write_record(rec)


# ---------------------------------------------------------- heartbeat ------
class RankHeartbeat:
    """Per-rank liveness lines so a wedged rank is diagnosable
    (BENCH_r0* postmortems: five rounds of silently wedged TPU runs).

    Appends JSONL lines {"ts", "kind": "heartbeat", "rank"/"epoch", ...}
    at most once per `interval` seconds; `beat(**fields)` is safe to
    call every loop tick. interval <= 0 disables."""

    def __init__(self, path: str, interval: float = 1.0):
        self.path = path
        self.interval = float(interval)
        self._last = 0.0
        self._f = None
        if self.interval > 0:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a", buffering=1)

    def due(self) -> bool:
        """True when the next beat would actually write — check before
        building an expensive snapshot payload every loop tick."""
        return (self._f is not None
                and time.time() - self._last >= self.interval)

    def beat(self, force: bool = False, **fields) -> bool:
        if self._f is None:
            return False
        now = time.time()
        if not force and now - self._last < self.interval:
            return False
        try:  # heartbeat_stall fault: the process stays alive but its
            # heartbeat goes silent — the wedged-rank signature the
            # launcher's stale-heartbeat detector exists to catch
            from ..framework import faults as _faults
            fa = _faults.check("heartbeat_stall")
            if fa is not None:
                self._stalled_until = now + float(
                    fa.params.get("sleep", 3600.0))
        except Exception:
            pass
        if now < getattr(self, "_stalled_until", 0.0):
            return False
        self._last = now
        rec = {"ts": round(now, 3), "kind": "heartbeat"}
        rec.update(fields)
        try:
            self._f.write(json.dumps(rec) + "\n")
        except Exception:
            return False
        return True

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            except Exception:
                pass
            self._f = None
