"""Fleet observability: cross-rank telemetry aggregation.

PR 12 made the runtime genuinely multi-rank (ZeRO-2/3, TP, 1F1B under
the elastic launcher) but telemetry stayed process-local: every rank
writes its own JSONL and nothing ever joins them, so the questions that
matter at fleet scale — *which rank is slow*, *is step time compute or
comm-wait*, *are the per-axis comm bytes balanced* — were unanswerable.
This module is the join:

- :class:`RankFileTailer` — incremental reader of ONE growing JSONL
  file: consumes whole lines only (a torn final line stays pending and
  is re-read complete on the next poll), survives mid-read rotation
  (the ``<path>.1`` sibling from ``JsonlExporter`` size rotation is
  drained before the fresh file), and folds a pre-existing ``.1``
  sibling in on first open. The PR-11 single-file tolerance,
  generalized to many concurrently-growing files.
- :class:`StragglerDetector` — persistent-skew state machine: a rank
  whose step time exceeds ``factor`` x the cross-rank median for
  ``min_steps`` CONSECUTIVE completed steps is flagged once per
  episode. This fires long before the PR-7 ``HangDetector`` ever could:
  a straggler still makes progress (its heartbeat keeps beating), it is
  just slow — silence-based detection is structurally blind to it.
- :class:`FleetAggregator` — the launcher-side consumer: tails every
  ``telemetry_rank<k>.jsonl`` / ``heartbeat_rank<k>.jsonl`` in a log
  directory, joins ``train.step`` spans across ranks on the global step
  index (the Trainer stamps it into the span's ``step`` label, which
  survives restarts — resumed runs continue the same step numbering),
  and computes per completed step: cross-rank skew (slowest minus
  median), per-rank comm-wait share (time inside ``comm.*`` spans vs
  step wall), plus per-axis comm-byte balance and heartbeat-gap
  timelines. Results export two ways at once: ``fleet.*`` gauges in the
  aggregating process's registry, and ``{"kind": "fleet"}`` JSONL
  records (same schema family as spans/heartbeats) for offline readers
  (``tools/fleet_report.py`` renders the same views file-side).

Everything here is pure stdlib + the metrics registry: no jax, no
device work — it runs in the launcher process (docs/OBSERVABILITY.md
"Fleet view").
"""
from __future__ import annotations

import glob
import json
import os
import re
import statistics
import sys
import time
from typing import Dict, List, Optional

from . import metrics as _obsm

__all__ = ["RankFileTailer", "StragglerDetector", "FleetAggregator"]

# bound on buffered per-rank state (steps awaiting the other ranks,
# trace->step maps, comm spans whose step isn't known yet): the
# aggregator must stay O(ranks * window) however long the run
_MAX_PENDING_STEPS = 512
_MAX_PENDING_TRACES = 2048


class RankFileTailer:
    """Incrementally read complete JSONL lines from one growing file.

    ``poll()`` returns the records appended since the last call.
    Guarantees, in the presence of a concurrent writer:

    - whole lines only: an unterminated tail (a line being appended
      RIGHT NOW, or a crash-time torn write) is held back and re-read
      on the next poll once the newline lands — never half-consumed,
      never lost;
    - interior garbage lines are skipped (counted in ``dropped``);
    - rotation-safe: when the writer rotates (``os.replace`` to
      ``<path>.1`` + fresh file — ``JsonlExporter`` semantics), the
      next poll drains the remainder of the OLD file from ``.1``
      before starting the new one, so no record is lost or doubled
      even when the fresh file grows past the old offset within one
      poll interval (the inode check catches that case);
    - a ``.1`` sibling that already exists at first open is folded in
      first, so a tailer attached mid-run still sees rotated history.
    """

    def __init__(self, path: str, ingest_existing_rotation: bool = True):
        self.path = path
        self.offset = 0
        self.dropped = 0          # undecodable interior lines
        self._ino: Optional[int] = None
        self._rot_done = not ingest_existing_rotation

    # ------------------------------------------------------------------
    @staticmethod
    def _read_complete(path: str, offset: int):
        """(complete lines, new offset) from byte ``offset``; the
        unterminated tail is NOT consumed. Binary mode keeps offsets
        byte-exact; json.loads accepts bytes."""
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
        cut = data.rfind(b"\n") + 1
        return data[:cut].splitlines(), offset + cut

    def _parse(self, lines) -> List[dict]:
        out = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                self.dropped += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    def poll(self) -> List[dict]:
        recs: List[dict] = []
        try:
            f = open(self.path, "rb")
        except OSError:
            return recs
        with f:
            # fstat the OPENED fd, not the path: a rotation landing
            # between a path-stat and the open would otherwise apply
            # the old file's byte offset to the new inode (losing the
            # old tail and double-counting the new file)
            st = os.fstat(f.fileno())
            if not self._rot_done:
                self._rot_done = True
                rot = self.path + ".1"
                if os.path.exists(rot):
                    try:
                        lines, _ = self._read_complete(rot, 0)
                        recs.extend(self._parse(lines))
                    except OSError:
                        pass
            if self._ino is not None and st.st_ino != self._ino:
                # rotated under us: drain the remainder of the old
                # file, which now lives at <path>.1 (one atomic
                # os.replace)
                rot = self.path + ".1"
                try:
                    if os.stat(rot).st_ino == self._ino:
                        lines, _ = self._read_complete(rot, self.offset)
                        recs.extend(self._parse(lines))
                except OSError:
                    pass
                self.offset = 0
            elif st.st_size < self.offset:
                self.offset = 0      # truncated: start over
            self._ino = st.st_ino
            f.seek(self.offset)
            data = f.read()
        cut = data.rfind(b"\n") + 1
        self.offset += cut
        recs.extend(self._parse(data[:cut].splitlines()))
        return recs


class StragglerDetector:
    """Persistent-skew detection over completed-step duration maps.

    Feed ``observe(step, durs)`` one ``{rank: seconds}`` map per
    completed step (every tracked rank reported). A rank above
    ``factor`` x the cross-rank median for ``min_steps`` consecutive
    steps is returned ONCE per episode (it re-arms after the rank
    returns under the threshold). Needs ``min_ranks`` ranks for the
    median to mean anything. Pure state machine — tests drive it with
    synthetic maps, no files, no clock."""

    def __init__(self, factor: float = 2.0, min_steps: int = 3,
                 min_ranks: int = 2):
        self.factor = float(factor)
        self.min_steps = max(1, int(min_steps))
        self.min_ranks = max(2, int(min_ranks))
        self._consec: Dict[str, int] = {}
        self._active: set = set()

    def observe(self, step: int, durs: Dict[str, float]) -> List[dict]:
        out = []
        if self.factor <= 0 or len(durs) < self.min_ranks:
            return out   # factor <= 0 disables detection
        med = statistics.median(durs.values())
        for rank, d in durs.items():
            if med > 0 and d > self.factor * med:
                c = self._consec.get(rank, 0) + 1
                self._consec[rank] = c
                if c >= self.min_steps and rank not in self._active:
                    self._active.add(rank)
                    out.append({"rank": rank, "step": int(step),
                                "dur_s": round(d, 6),
                                "median_s": round(med, 6),
                                "ratio": round(d / med, 3),
                                "consecutive": c})
            else:
                self._consec[rank] = 0
                self._active.discard(rank)
        return out


def _rank_of(path: str) -> str:
    m = re.search(r"rank(\d+)", os.path.basename(path))
    return m.group(1) if m else os.path.basename(path)


class FleetAggregator:
    """Tail a directory of per-rank telemetry/heartbeat JSONL files and
    compute the fleet view (module docstring). Drive it by calling
    :meth:`poll` periodically (the launcher does, at heartbeat cadence);
    each poll ingests whatever every rank appended and emits:

    gauges (aggregating process's registry)
        ``fleet.step_skew_seconds``          slowest - median, last
                                             completed step
        ``fleet.step_time_seconds``          per-rank last step wall
                                             (label ``rank``)
        ``fleet.comm_wait_share``            per-rank comm-wait / step
                                             wall (label ``rank``)
        ``fleet.comm_bytes_imbalance``       per-axis max/mean of
                                             cumulative comm bytes
                                             across ranks (label
                                             ``axis``; 1.0 = balanced)
        ``fleet.heartbeat_gap_seconds``      per-rank worst observed
                                             inter-beat gap (label
                                             ``rank``)
        ``robustness.stragglers_detected``   counter, label ``rank``

    JSONL records (``out_path``, one object per line)
        ``{"kind": "fleet", "event": "step", "step", "durs",
        "skew_s", "median_s", "slowest_rank", "comm_wait_share"}``
        per completed step;
        ``{"kind": "fleet", "event": "straggler", ...,
        "dominant_span"}`` per detector firing;
        ``{"kind": "fleet", "event": "comm_balance", "axis",
        "bytes", "imbalance"}`` and ``{"kind": "fleet", "event":
        "heartbeat_gap", "rank", "gap_s"}`` when those move.
    """

    TELEMETRY_GLOB = "telemetry_rank*.jsonl"
    HEARTBEAT_GLOB = "heartbeat_rank*.jsonl"

    def __init__(self, log_dir: str, out_path: Optional[str] = None,
                 straggler_factor: float = 2.0,
                 straggler_steps: int = 3,
                 expected_ranks: Optional[int] = None,
                 registry: Optional[_obsm.MetricRegistry] = None,
                 now_fn=time.time, log=None, on_step=None):
        self.log_dir = os.path.abspath(log_dir)
        # known world size: steps join only once every expected rank's
        # telemetry file is visible — without it, ranks that boot a few
        # seconds late (the import/compile window) would be left out of
        # the early joins and their prefix steps never re-joined
        self.expected_ranks = int(expected_ranks) if expected_ranks \
            else None
        self.out_path = out_path if out_path is not None else \
            os.path.join(self.log_dir, "fleet.jsonl")
        self._reg = registry or _obsm.get_registry()
        self._now = now_fn
        self._log = log or (lambda msg: print(msg, file=sys.stderr))
        self.detector = StragglerDetector(factor=straggler_factor,
                                          min_steps=straggler_steps)
        self._tailers: Dict[str, RankFileTailer] = {}
        self._hb_tailers: Dict[str, RankFileTailer] = {}
        # per-rank join state
        self._steps: Dict[str, Dict[int, dict]] = {}   # rank -> step ->
        #   {"dur", "start", "children": {name: dur}, "comm_s"}
        self._trace_step: Dict[str, Dict[str, int]] = {}
        self._orphan_comm: Dict[str, Dict[str, float]] = {}
        self._comm_bytes: Dict[str, Dict[str, float]] = {}  # rank->axis
        self._last_beat: Dict[str, float] = {}
        self._worst_gap: Dict[str, float] = {}
        self._completed_through = -1    # last step joined + emitted
        self.stragglers: List[dict] = []
        # the serving autopilot's audit stream (controller.py): control
        # decisions and SLO breaches collected fleet-side so one
        # launcher view audits what every rank's control loop did.
        # Bounded like the trace joins; whole records only (the tailer
        # never yields a torn line — tests/test_fleet.py asserts it).
        self.control_records: List[dict] = []
        self.slo_breaches: List[dict] = []
        # per-joined-step feed for launcher-side consumers (the
        # mitigation controller's cost model + comm-wait-inversion
        # detector): on_step(step, durs, comm_wait_share)
        self.on_step = on_step
        # ranks evicted by an exclude-and-restart mitigation: their
        # files stay on disk (history) but they leave the join — a
        # dead rank must not stall every future step join
        self._retired: set = set()
        self._out = None
        self._warned: set = set()

    # --------------------------------------------------------- output --
    def _emit(self, rec: dict):
        rec = {"ts": round(self._now(), 6), "kind": "fleet", **rec}
        if self._out is None:
            d = os.path.dirname(os.path.abspath(self.out_path))
            try:
                os.makedirs(d, exist_ok=True)
                self._out = open(self.out_path, "a", buffering=1)
            except OSError:
                return
        try:
            self._out.write(json.dumps(rec) + "\n")
        except (OSError, ValueError):
            pass

    def close(self):
        if self._out is not None:
            try:
                self._out.close()
            except OSError:
                pass
            self._out = None

    # --------------------------------------------------------- ingest --
    def _discover(self):
        for path in glob.glob(os.path.join(self.log_dir,
                                           self.TELEMETRY_GLOB)):
            if path.endswith(".jsonl") and path not in self._tailers:
                self._tailers[path] = RankFileTailer(path)
        for path in glob.glob(os.path.join(self.log_dir,
                                           self.HEARTBEAT_GLOB)):
            if path not in self._hb_tailers:
                self._hb_tailers[path] = RankFileTailer(path)

    def _rank_state(self, rank: str) -> Dict[int, dict]:
        return self._steps.setdefault(rank, {})

    def retire_rank(self, rank) -> None:
        """Drop a rank from the fleet join (exclude-and-restart
        mitigation): its pending state is discarded and future records
        from its files are ignored, so the survivors' steps keep
        joining instead of waiting forever on a rank that will never
        report again. The expected world shrinks with it."""
        rank = str(rank)
        self._retired.add(rank)
        self._steps.pop(rank, None)
        self._trace_step.pop(rank, None)
        self._orphan_comm.pop(rank, None)
        self._comm_bytes.pop(rank, None)
        if self.expected_ranks and self.expected_ranks > 1:
            self.expected_ranks -= 1
        self._emit({"event": "rank_retired", "rank": rank})

    def _prune(self, rank: str):
        steps = self._steps.get(rank) or {}
        while len(steps) > _MAX_PENDING_STEPS:
            steps.pop(min(steps))
        traces = self._trace_step.get(rank) or {}
        while len(traces) > _MAX_PENDING_TRACES:
            traces.pop(next(iter(traces)))
        orphans = self._orphan_comm.get(rank) or {}
        while len(orphans) > _MAX_PENDING_TRACES:
            orphans.pop(next(iter(orphans)))

    def _ingest_span(self, rank: str, rec: dict):
        name = rec.get("name") or ""
        labels = rec.get("labels") or {}
        trace = rec.get("trace")
        dur = float(rec.get("dur") or 0.0)
        if name == "train.step":
            step = labels.get("step")
            if step is None:
                return
            step = int(step)
            st = self._rank_state(rank).setdefault(step, {
                "children": {}, "comm_s": 0.0})
            st["dur"] = dur
            st["start"] = float(rec.get("start") or 0.0)
            if trace:
                self._trace_step.setdefault(rank, {})[trace] = step
                # comm spans that arrived before their step span
                pend = self._orphan_comm.get(rank, {}).pop(trace, None)
                if pend:
                    st["comm_s"] += pend
            self._prune(rank)
        elif name.startswith("train."):
            # phase spans (data/dispatch/loss_sync/...): keep per-step
            # child durations so a straggler's dominant phase is
            # nameable; they also bind the trace id to the step index
            # for comm spans, which carry no step label themselves
            step = labels.get("step")
            if step is not None and trace:
                self._trace_step.setdefault(rank, {})[trace] = int(step)
                st = self._rank_state(rank).setdefault(int(step), {
                    "children": {}, "comm_s": 0.0})
                ch = st["children"]
                ch[name] = ch.get(name, 0.0) + dur
                pend = self._orphan_comm.get(rank, {}).pop(trace, None)
                if pend:
                    st["comm_s"] += pend
                self._prune(rank)
        elif name.startswith("comm."):
            step = self._trace_step.get(rank, {}).get(trace) \
                if trace else None
            if step is not None:
                st = self._rank_state(rank).setdefault(step, {
                    "children": {}, "comm_s": 0.0})
                st["comm_s"] += dur
                ch = st["children"]
                ch[name] = ch.get(name, 0.0) + dur
            elif trace:
                orphans = self._orphan_comm.setdefault(rank, {})
                orphans[trace] = orphans.get(trace, 0.0) + dur
                self._prune(rank)

    def _ingest_control(self, rank: str, rec: dict):
        """Collect a control-loop decision (whole-record or nothing:
        the tailer's line framing guarantees no torn audit entries)
        and re-emit it into the fleet stream so the single launcher
        file carries the cross-rank decision history too."""
        keep = dict(rec, rank=rank)
        self.control_records.append(keep)
        del self.control_records[:-_MAX_PENDING_TRACES]
        self._emit({"event": "control", "rank": rank,
                    "seq": rec.get("seq"), "rule": rec.get("rule"),
                    "action": rec.get("action"),
                    "tier": rec.get("tier")})

    def _ingest_sample(self, rank: str, rec: dict):
        if rec.get("name") != "comm.bytes":
            return
        ax = (rec.get("labels") or {}).get("axis")
        if ax is None:
            return
        per_axis = self._comm_bytes.setdefault(rank, {})
        # cumulative counter, one series per (op, axis): last snapshot
        # per op wins; fold ops into the axis total at compute time
        op = (rec.get("labels") or {}).get("op", "?")
        per_axis[(ax, op)] = float(rec.get("value") or 0.0)

    def _ingest_beat(self, rank: str, rec: dict):
        ts = rec.get("ts")
        if ts is None:
            return
        ts = float(ts)
        prev = self._last_beat.get(rank)
        if prev is not None and ts > prev:
            gap = ts - prev
            if gap > self._worst_gap.get(rank, 0.0):
                self._worst_gap[rank] = gap
        if prev is None or ts > prev:
            self._last_beat[rank] = ts

    # -------------------------------------------------------- compute --
    def _join_steps(self):
        """Emit every step all tracked ranks have reported, in order."""
        if len(self._steps) < max(2, self.expected_ranks or 2):
            return
        ranks = sorted(self._steps, key=lambda r: (len(r), r))
        while True:
            candidate = self._completed_through + 1
            have = [r for r in ranks
                    if (self._steps[r].get(candidate) or {}).get("dur")
                    is not None]
            if len(have) < len(ranks):
                # steps are consecutive per rank; if every rank is
                # already past the candidate (resume gap), skip forward
                nxt = [min((s for s in self._steps[r]
                            if s > candidate
                            and self._steps[r][s].get("dur") is not None),
                           default=None) for r in ranks]
                if all(n is not None for n in nxt) \
                        and min(nxt) > candidate:
                    self._completed_through = min(nxt) - 1
                    continue
                return
            durs = {r: float(self._steps[r][candidate]["dur"])
                    for r in ranks}
            comm = {r: float(self._steps[r][candidate].get("comm_s", 0.0))
                    for r in ranks}
            self._emit_step(candidate, durs, comm)
            for r in ranks:
                self._steps[r].pop(candidate, None)
            self._completed_through = candidate

    def _emit_step(self, step: int, durs: Dict[str, float],
                   comm: Dict[str, float]):
        med = statistics.median(durs.values())
        slowest = max(durs, key=durs.get)
        skew = durs[slowest] - med
        share = {r: (comm[r] / durs[r] if durs[r] > 0 else 0.0)
                 for r in durs}
        g_skew = self._reg.gauge(
            "fleet.step_skew_seconds", unit="s",
            help="slowest minus median rank wall time, last completed "
                 "step")
        g_skew.set(skew)
        g_time = self._reg.gauge("fleet.step_time_seconds", unit="s")
        g_share = self._reg.gauge("fleet.comm_wait_share")
        for r in durs:
            g_time.set(durs[r], rank=r)
            g_share.set(share[r], rank=r)
        self._emit({"event": "step", "step": step,
                    "durs": {r: round(d, 6) for r, d in durs.items()},
                    "median_s": round(med, 6),
                    "skew_s": round(skew, 6),
                    "slowest_rank": slowest,
                    "comm_wait_share": {r: round(s, 4)
                                        for r, s in share.items()}})
        if self.on_step is not None:
            try:
                self.on_step(step, dict(durs), dict(share))
            except Exception:
                pass   # consumers must never kill the aggregator
        for hit in self.detector.observe(step, durs):
            dominant = self._dominant_span(hit["rank"], step)
            hit["dominant_span"] = dominant
            # the flagged rank's comm-wait share at the flagging step:
            # the mitigation controller's classification evidence (a
            # comm-dominated straggler is a degraded NIC, not a slow
            # core)
            hit["comm_wait_share"] = round(share.get(hit["rank"],
                                                     0.0), 4)
            self.stragglers.append(hit)
            self._reg.counter(
                "robustness.stragglers_detected",
                help="ranks flagged by the fleet persistent-skew "
                     "detector").inc(rank=str(hit["rank"]))
            self._emit({"event": "straggler", **hit})
            self._log(
                f"[fleet] straggler: rank {hit['rank']} at step {step} "
                f"— {hit['dur_s'] * 1e3:.1f}ms vs median "
                f"{hit['median_s'] * 1e3:.1f}ms "
                f"({hit['ratio']:.1f}x, {hit['consecutive']} "
                f"consecutive steps; dominant span "
                f"{dominant or 'unknown'!r})")

    def _dominant_span(self, rank: str, step: int) -> Optional[str]:
        # called from _emit_step BEFORE the step entry is popped
        st = (self._steps.get(rank) or {}).get(step) or {}
        children = st.get("children") or {}
        if not children:
            return None
        return max(children, key=children.get)

    def _comm_balance(self):
        if len(self._comm_bytes) < 2:
            return
        axes: Dict[str, Dict[str, float]] = {}
        for rank, per in self._comm_bytes.items():
            for (ax, _op), v in per.items():
                axes.setdefault(ax, {}).setdefault(rank, 0.0)
                axes[ax][rank] += v
        g = self._reg.gauge(
            "fleet.comm_bytes_imbalance",
            help="per-axis max/mean cumulative comm bytes across "
                 "ranks; 1.0 = balanced")
        for ax, by_rank in axes.items():
            vals = list(by_rank.values())
            mean = sum(vals) / len(vals)
            imb = (max(vals) / mean) if mean > 0 else 1.0
            g.set(imb, axis=ax)
            # one record per 1% imbalance move, not one per poll —
            # cumulative byte counters grow every step
            key = ("comm", ax, round(imb, 2))
            if key not in self._warned:
                self._warned.add(key)
                self._emit({"event": "comm_balance", "axis": ax,
                            "bytes": {r: int(v)
                                      for r, v in by_rank.items()},
                            "imbalance": round(imb, 4)})

    def _heartbeat_gaps(self):
        g = self._reg.gauge("fleet.heartbeat_gap_seconds", unit="s")
        for rank, gap in self._worst_gap.items():
            g.set(gap, rank=rank)
            key = ("hb", rank, int(gap))   # one record per whole second
            if gap >= 2.0 and key not in self._warned:
                self._warned.add(key)
                self._emit({"event": "heartbeat_gap", "rank": rank,
                            "gap_s": round(gap, 3)})

    # ----------------------------------------------------------- poll --
    def poll(self) -> int:
        """Ingest everything appended since the last poll; returns the
        number of records consumed. This is the aggregator tail loop —
        registered hot path in tools/graft_lint/config.py: it runs at
        heartbeat cadence inside the launcher babysit loop, so it must
        stay file-I/O-only (no device work, no blocking syncs)."""
        self._discover()
        n = 0
        for path, tailer in self._tailers.items():
            rank = _rank_of(path)
            if rank in self._retired:
                tailer.poll()   # keep draining; records are ignored
                continue
            for rec in tailer.poll():
                n += 1
                # per-record guard: a line that parses as JSON but has
                # a wrong-typed field (hand-written heartbeats,
                # interleaved garbage — the corruption this layer
                # exists to diagnose) must not take down the launcher
                # babysit loop that hosts this aggregator
                try:
                    kind = rec.get("kind")
                    if kind == "span":
                        self._ingest_span(rank, rec)
                    elif kind == "heartbeat":
                        self._ingest_beat(rank, rec)
                    elif kind == "control":
                        self._ingest_control(rank, rec)
                    elif kind == "slo_breach":
                        self.slo_breaches.append(dict(rec, rank=rank))
                        del self.slo_breaches[:-_MAX_PENDING_TRACES]
                    elif rec.get("name"):
                        # registry sample lines carry the METRIC kind
                        # (counter/gauge/histogram) in "kind"
                        self._ingest_sample(rank, rec)
                except (TypeError, ValueError, KeyError):
                    tailer.dropped += 1
        for path, tailer in self._hb_tailers.items():
            rank = _rank_of(path)
            for rec in tailer.poll():
                n += 1
                try:
                    if rec.get("kind") == "heartbeat":
                        self._ingest_beat(rank, rec)
                except (TypeError, ValueError, KeyError):
                    tailer.dropped += 1
        if n:
            self._join_steps()
            self._comm_balance()
            self._heartbeat_gaps()
        return n
