"""paddle.geometric parity (python/paddle/geometric/): graph
message-passing primitives. TPU-native: jax.ops.segment_* ARE the
gather-scatter kernels the reference implements in CUDA
(phi/kernels/gpu/graph_send_recv_kernel.cu) — one fused scatter per op,
jit/grad friendly. Segment counts are static (num_segments from the
destination-node count), which is exactly what XLA wants."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .ops._dispatch import apply
from .ops.creation import _coerce
from .tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv",
           "sample_neighbors", "reindex_graph"]


def _num_segments(seg, out_size):
    if out_size is not None:
        return int(out_size)
    return int(np.asarray(_coerce(seg)._value).max()) + 1


def _segment(op, data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    fn = {"sum": jax.ops.segment_sum, "mean": None,
          "max": jax.ops.segment_max, "min": jax.ops.segment_min}[op]

    def run(d, s):
        s = s.astype(jnp.int32)
        if op == "mean":
            tot = jax.ops.segment_sum(d, s, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(s, d.dtype), s,
                                      num_segments=n)
            shape = (n,) + (1,) * (d.ndim - 1)
            return tot / jnp.maximum(cnt.reshape(shape), 1)
        out = fn(d, s, num_segments=n)
        if op in ("max", "min"):
            # empty segments: paddle fills 0, jax fills +/-inf
            cnt = jax.ops.segment_sum(jnp.ones_like(s, jnp.int32), s,
                                      num_segments=n)
            shape = (n,) + (1,) * (d.ndim - 1)
            out = jnp.where(cnt.reshape(shape) > 0, out, 0)
        return out
    return apply(run, _coerce(data), _coerce(segment_ids))


def segment_sum(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_sum."""
    return _segment("sum", data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_mean."""
    return _segment("mean", data, segment_ids)


def segment_max(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_max (empty segments -> 0)."""
    return _segment("max", data, segment_ids)


def segment_min(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_min (empty segments -> 0)."""
    return _segment("min", data, segment_ids)


def _reduce_to(op, msgs, dst, n):
    if op == "sum":
        return jax.ops.segment_sum(msgs, dst, num_segments=n)
    if op == "mean":
        tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(dst, msgs.dtype), dst,
                                  num_segments=n)
        return tot / jnp.maximum(cnt.reshape((n,) + (1,) *
                                             (msgs.ndim - 1)), 1)
    fn = jax.ops.segment_max if op == "max" else jax.ops.segment_min
    out = fn(msgs, dst, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(dst, jnp.int32), dst,
                              num_segments=n)
    return jnp.where(cnt.reshape((n,) + (1,) * (msgs.ndim - 1)) > 0,
                     out, 0)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] along edges and reduce at dst (parity:
    paddle.geometric.send_u_recv; phi graph_send_recv kernel)."""
    n = out_size if out_size is not None else _coerce(x).shape[0]

    def run(xv, src, dst):
        msgs = xv[src.astype(jnp.int32)]
        return _reduce_to(reduce_op, msgs, dst.astype(jnp.int32), int(n))
    return apply(run, _coerce(x), _coerce(src_index), _coerce(dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node features combined with edge features, then reduced at dst
    (parity: paddle.geometric.send_ue_recv)."""
    n = out_size if out_size is not None else _coerce(x).shape[0]
    comb = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}[message_op]

    def run(xv, yv, src, dst):
        msgs = comb(xv[src.astype(jnp.int32)], yv)
        return _reduce_to(reduce_op, msgs, dst.astype(jnp.int32), int(n))
    return apply(run, _coerce(x), _coerce(y), _coerce(src_index),
                 _coerce(dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge messages x[src] (op) y[dst] (parity:
    paddle.geometric.send_uv)."""
    comb = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}[message_op]

    def run(xv, yv, src, dst):
        return comb(xv[src.astype(jnp.int32)], yv[dst.astype(jnp.int32)])
    return apply(run, _coerce(x), _coerce(y), _coerce(src_index),
                 _coerce(dst_index))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling from a CSC graph (parity:
    paddle.geometric.sample_neighbors, phi graph_sample_neighbors).
    Host-side op by design: it runs in the dataloader/graph-sampler
    stage (variable-size outputs cannot live under jit), like the
    reference's CPU kernel in a GraphSampler worker."""
    # seeded from the framework generator: paddle.seed makes sampling
    # reproducible, like the reference kernel's seeded curand stream
    from .framework.random import default_generator
    sub = default_generator().split()
    rng = np.random.default_rng(
        int(jax.random.randint(sub, (), 0, 2 ** 31 - 1)))
    rowv = np.asarray(_coerce(row)._value)
    ptr = np.asarray(_coerce(colptr)._value)
    nodes = np.asarray(_coerce(input_nodes)._value).reshape(-1)
    eidv = (np.asarray(_coerce(eids)._value)
            if eids is not None else None)
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        lo, hi = int(ptr[v]), int(ptr[v + 1])
        neigh = rowv[lo:hi]
        idx = np.arange(lo, hi)
        if sample_size != -1 and (hi - lo) > sample_size:
            pick = rng.choice(hi - lo, size=sample_size, replace=False)
            neigh, idx = neigh[pick], idx[pick]
        out_n.append(neigh)
        out_c.append(len(neigh))
        if eidv is not None:
            out_e.append(eidv[idx])
    neighbors = Tensor(jnp.asarray(
        np.concatenate(out_n) if out_n else np.empty(0, rowv.dtype)))
    counts = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        if eidv is None:
            raise ValueError("return_eids=True requires eids")
        return neighbors, counts, Tensor(jnp.asarray(
            np.concatenate(out_e) if out_e else np.empty(0, eidv.dtype)))
    return neighbors, counts


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Relabel a sampled subgraph to contiguous local ids (parity:
    paddle.geometric.reindex_graph, phi graph_reindex). Host-side for
    the same reason as sample_neighbors."""
    xv = np.asarray(_coerce(x)._value).reshape(-1)
    nb = np.asarray(_coerce(neighbors)._value).reshape(-1)
    cnt = np.asarray(_coerce(count)._value).reshape(-1)
    mapping = {int(v): i for i, v in enumerate(xv)}
    order = list(xv)
    for v in nb:
        v = int(v)
        if v not in mapping:
            mapping[v] = len(order)
            order.append(v)
    reindex_src = np.asarray([mapping[int(v)] for v in nb],
                             np.int64)
    reindex_dst = np.repeat(np.arange(len(xv), dtype=np.int64), cnt)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.asarray(order, xv.dtype))))
