"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas.

Top-level namespace parity: python/paddle/__init__.py. The import graph is
kept light: `import paddle_tpu as paddle` gives `paddle.Tensor`,
`paddle.to_tensor`, the op library, `paddle.nn`, `paddle.optimizer`,
`paddle.distributed` (Fleet equivalent), `paddle.jit`, `paddle.amp`,
`paddle.io`, `paddle.vision`, `paddle.inference`.
"""
from __future__ import annotations

import jax as _jax

# Paddle dtype parity needs int64/float64 tensors (paddle defaults python
# ints to int64); enable x64 before any array is created. Compute-path code
# explicitly uses float32/bfloat16, so the TPU hot path is unaffected.
_jax.config.update("jax_enable_x64", True)
# Paddle/cuBLAS semantics: float32 matmuls accumulate in float32. JAX's
# default lets the backend pick (bf16 passes on TPU); force f32 for parity —
# the bf16 hot path opts in explicitly via amp/bfloat16 params instead.
# NOTE: Pallas kernels must pin their own per-dot precision —
# kernels/_common.mxu_precision — because Mosaic rejects bf16 matmuls
# carrying the global fp32 contract precision ("Bad lhs type" on v5e).
_jax.config.update("jax_default_matmul_precision", "highest")

# Persistent XLA compile cache (parity role: Paddle Inference's engine/
# program caches + CINN's compilation cache). On the tunnelled TPU sandbox
# every compile is a remote RPC, so warm-starting from disk is the
# difference between a 10-minute and a 10-second bench bring-up.
import os as _os
_cache_dir = _os.environ.get("PADDLE_TPU_XLA_CACHE",
                             _os.path.expanduser("~/.cache/paddle_tpu_xla"))
if _cache_dir and _cache_dir != "0":
    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # cache is best-effort; never block import
        pass

__version__ = "0.1.0"

from .framework import dtype as _dtype_mod
from .framework.dtype import (
    bool_ as bool,  # noqa: A001 — paddle exposes paddle.bool
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, finfo, iinfo,
)
from .framework.place import (
    CPUPlace, TPUPlace, XLAPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
    set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
)
from .framework.random import (seed, get_rng_state, set_rng_state,
                               get_cuda_rng_state, set_cuda_rng_state)
from .framework.flags import set_flags, get_flags
from .framework import random as _random_mod

from .tensor import Tensor, Parameter, to_tensor
from .autograd.grad_mode import no_grad, enable_grad, is_grad_enabled, set_grad_enabled
from .autograd import grad
from . import autograd

# op library — star-exported at top level (paddle.add, paddle.matmul, ...)
from .ops import *  # noqa: F401,F403
from . import ops

from . import nn
from . import regularizer
from . import optimizer
from . import amp
from . import io
from . import metric
from .framework_io import save, load
from .nn.initializer import ParamAttr


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone trainable Parameter (parity:
    python/paddle/tensor/creation.py create_parameter — LayerHelper path
    without requiring a Layer)."""
    from .nn.layer_base import Layer
    helper = Layer()
    p = helper.create_parameter(shape, attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    if p is not None and name is not None:
        p.name = name
    return p

from . import jit
from . import static
from .static.api import enable_static, disable_static, in_dynamic_mode
from . import device
from . import vision
from . import inference
from . import incubate
from . import profiler
from .hapi import Model, summary
from .hapi.flops import flops
from . import hub
from . import text
from . import base
from . import fluid
from . import sysconfig
from . import geometric
from .hapi import callbacks

from . import distributed
from .distributed.parallel import DataParallel

from . import fft
from . import signal
from . import multiprocessing
from . import sparse
from . import distribution
from . import audio
from . import utils
from . import version
from . import onnx
from . import generation
from . import diffusion
from . import observability


def is_grad_enabled_():
    return is_grad_enabled()


def get_default_place():
    from .framework.place import _default_place
    return _default_place()


from .framework.place import is_compiled_with_rocm  # noqa: E402


def is_compiled_with_custom_device(device_type=None):
    from . import device as _device
    return bool(_device.get_all_custom_device_type())


def device_count():
    import jax as _jax
    return len(_jax.devices())


def disable_signal_handler():
    """Parity shim: paddle installs C++ signal handlers; here python's
    default handlers are already in charge, so this is a no-op."""


class LazyGuard:
    """Parity: paddle.LazyGuard — upstream defers parameter
    materialization. Initializers here are cheap jax ops, so the guard
    is a transparent context (parameters exist immediately, which is a
    superset of the lazy contract for user code)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Parity: paddle.set_printoptions (python/paddle/tensor/to_string.py).
    Tensor repr here prints through numpy, so numpy's printoptions ARE the
    printoptions."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


# paddle.dtype: dtypes in this framework ARE numpy dtype objects
import numpy as _np_mod  # noqa: E402
dtype = _np_mod.dtype


def in_static_mode():
    """Parity: paddle.in_static_mode (inverse of in_dynamic_mode)."""
    return not in_dynamic_mode()


def is_compiled_with_cinn():
    """Parity: CINN's role is subsumed by XLA here (SURVEY §2.1)."""
    return False


def batch(reader, batch_size, drop_last=False):
    """Parity: paddle.batch — legacy reader-composer (python/paddle/
    batch.py): wraps a sample reader into a batched reader."""
    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


from .amp import is_autocast_enabled, get_autocast_dtype  # noqa: E402
amp_guard = amp.amp_guard
