"""paddle.fluid compat namespace (the pre-2.6 spelling of paddle.base;
a vast amount of published Paddle code still imports it)."""
from ..base import (core, Program, Executor, program_guard,  # noqa: F401
                    default_main_program, default_startup_program,
                    global_scope, scope_guard, Scope, CPUPlace, CUDAPlace,
                    Tensor, no_grad, dygraph_guard, framework)
from ..static import nn as layers  # noqa: F401  (fluid.layers ~ static.nn)
from .. import io  # noqa: F401
from ..optimizer import Optimizer  # noqa: F401


class dygraph:
    """fluid.dygraph compat: to_variable/guard."""

    @staticmethod
    def to_variable(value, name=None, zero_copy=None):
        from ..ops.creation import to_tensor
        return to_tensor(value)

    guard = staticmethod(dygraph_guard)
