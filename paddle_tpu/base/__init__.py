"""paddle.base compat namespace (upstream renamed paddle.fluid ->
paddle.base in 2.6; both spellings appear in real user code). Maps the
high-traffic symbols onto their modern homes so ported scripts import
cleanly."""
from ..framework.core import core  # noqa: F401
from ..static.api import (  # noqa: F401
    Program, Executor, program_guard, default_main_program,
    default_startup_program, global_scope, scope_guard, Scope)
from ..framework.place import CPUPlace, TPUPlace, XLAPlace  # noqa: F401
from ..framework.place import TPUPlace as CUDAPlace  # noqa: F401
from ..tensor import Tensor  # noqa: F401
from ..autograd.grad_mode import no_grad  # noqa: F401
from .. import framework  # noqa: F401


def dygraph_guard(*a, **k):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()
