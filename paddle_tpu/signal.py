"""paddle.signal parity — stft / istft.

Reference parity: python/paddle/signal.py (frame/overlap_add + fft
kernels). TPU-native: framing is a gather into [*, frames, frame_length]
(XLA turns it into strided slices), the FFT is an XLA FFT HLO, and
overlap-add uses a scatter-add — all jit/grad friendly through apply().
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .ops._dispatch import apply
from .ops.creation import _coerce
from .tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along the time axis
    (paddle.signal.frame). axis=-1: time last, output
    [..., frame_length, num_frames]; axis=0: time first, output
    [num_frames, frame_length, ...] (the reference's mirrored layout)."""
    def fn(v):
        # for 1-D input axes 0 and -1 coincide; the OUTPUT layout follows
        # the axis value the caller passed (paddle semantics)
        first = axis == 0 or (v.ndim > 1 and axis == -v.ndim)
        if not first and axis not in (-1, v.ndim - 1):
            raise ValueError("frame: axis must be 0 or -1")
        vt = jnp.moveaxis(v, 0, -1) if first else v
        n = vt.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = jnp.moveaxis(vt[..., idx], -2, -1)  # [..., fl, num]
        if first:
            out = jnp.moveaxis(out, (-1, -2), (0, 1))  # [num, fl, ...]
        return out
    return apply(fn, _coerce(x), _name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame. axis=-1: x [..., frame_length, num_frames];
    axis=0: x [num_frames, frame_length, ...]."""
    def fn(v):
        first = axis == 0 or (v.ndim > 2 and axis == -v.ndim)
        if not first and axis not in (-1, v.ndim - 1):
            raise ValueError("overlap_add: axis must be 0 or -1")
        vt = jnp.moveaxis(v, (0, 1), (-1, -2)) if first else v
        fl, num = vt.shape[-2], vt.shape[-1]
        out_len = (num - 1) * hop_length + fl
        starts = jnp.arange(num) * hop_length
        flat = jnp.moveaxis(vt, -1, -2).reshape(*vt.shape[:-2], num * fl)
        # scatter-add frames into the output timeline
        out = jnp.zeros((*vt.shape[:-2], out_len), vt.dtype)
        idx2 = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        out = out.at[..., idx2].add(flat)
        if first:
            out = jnp.moveaxis(out, -1, 0)
        return out
    return apply(fn, _coerce(x), _name="overlap_add")


def _window_arr(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    from .tensor import Tensor as T
    if isinstance(window, T):
        return window._value.astype(dtype)
    return jnp.asarray(window, dtype)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """paddle.signal.stft parity: returns [..., n_fft//2+1 or n_fft,
    frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xv = _coerce(x)

    def fn(v, *w):
        win = w[0] if w else jnp.ones((win_length,), v.dtype)
        if win_length < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        sig = v
        if center:
            pad = n_fft // 2
            sig = jnp.pad(sig, [(0, 0)] * (sig.ndim - 1) + [(pad, pad)],
                          mode=pad_mode)
        n = sig.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = sig[..., idx] * win  # [..., frames, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        return jnp.moveaxis(spec, -2, -1)  # [..., freq, frames]

    args = [xv]
    if window is not None:
        args.append(_coerce(window))
    return apply(fn, *args, _name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """paddle.signal.istft parity (window-envelope-normalized overlap-add)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xv = _coerce(x)

    def fn(v, *w):
        win = w[0] if w else jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        spec = jnp.moveaxis(v, -1, -2)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        frames = frames * win
        num = frames.shape[-2]
        out_len = (num - 1) * hop_length + n_fft
        starts = jnp.arange(num) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        flat = frames.reshape(*frames.shape[:-2], num * n_fft)
        sig = jnp.zeros((*frames.shape[:-2], out_len), frames.dtype)
        sig = sig.at[..., idx].add(flat)
        env = jnp.zeros((out_len,), frames.dtype)
        env = env.at[idx].add(jnp.tile(win * win, num))
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:out_len - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    args = [xv]
    if window is not None:
        args.append(_coerce(window))
    return apply(fn, *args, _name="istft")
