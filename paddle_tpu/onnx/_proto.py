"""Minimal protobuf wire-format writer for ONNX ModelProto.

The environment ships no `onnx` (or `protobuf`) package, but ONNX files
are plain protobuf — and protobuf's wire format is simple enough to emit
directly: varints, and length-delimited submessages/bytes. This module
hand-encodes exactly the subset of onnx.proto the exporter needs
(ModelProto / GraphProto / NodeProto / TensorProto / ValueInfoProto /
AttributeProto, field numbers per the public onnx/onnx.proto schema).

A matching *independent* reader (`parse_model`) decodes the same subset
so tests can round-trip files without the onnx package; any
spec-compliant consumer (onnxruntime, netron) reads the output directly.
"""
from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

# onnx.TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64 = 1, 2, 3, 6, 7
STRING, BOOL, FLOAT16, DOUBLE = 8, 9, 10, 11
BFLOAT16 = 16

_NP2ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16,
    np.dtype(np.int64): INT64,
    np.dtype(np.int32): INT32,
    np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8,
    np.dtype(np.bool_): BOOL,
}


def np_to_onnx_dtype(dt) -> int:
    dt = np.dtype(dt)
    if str(dt) == "bfloat16":
        return BFLOAT16
    if dt not in _NP2ONNX:
        raise ValueError(f"no ONNX dtype for {dt}")
    return _NP2ONNX[dt]


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # protobuf encodes negatives as 10-byte varints
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def f_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def f_str(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode("utf-8"))


def f_msg(field: int, body: bytes) -> bytes:
    return f_bytes(field, body)


def f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


# ---------------------------------------------------------------------------
# onnx messages
# ---------------------------------------------------------------------------

def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    body = b"".join(f_varint(1, d) for d in arr.shape)
    body += f_varint(2, np_to_onnx_dtype(arr.dtype))
    body += f_str(8, name)
    body += f_bytes(9, np.ascontiguousarray(arr).tobytes())  # raw_data
    return body


def attr_int(name: str, v: int) -> bytes:
    return f_str(1, name) + f_varint(3, v) + f_varint(20, 2)    # INT


def attr_float(name: str, v: float) -> bytes:
    return f_str(1, name) + f_float(2, v) + f_varint(20, 1)     # FLOAT


def attr_ints(name: str, vs: Sequence[int]) -> bytes:
    out = f_str(1, name)
    for v in vs:
        out += f_varint(8, v)
    return out + f_varint(20, 7)                                # INTS


def attr_str(name: str, s: str) -> bytes:
    return f_str(1, name) + f_bytes(4, s.encode()) + f_varint(20, 3)


def attr_tensor(name: str, t: bytes) -> bytes:
    return f_str(1, name) + f_msg(5, t) + f_varint(20, 4)       # TENSOR


def node_proto(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
               name: str = "", attrs: Sequence[bytes] = ()) -> bytes:
    body = b"".join(f_str(1, i) for i in inputs)
    body += b"".join(f_str(2, o) for o in outputs)
    if name:
        body += f_str(3, name)
    body += f_str(4, op_type)
    body += b"".join(f_msg(5, a) for a in attrs)
    return body


def value_info(name: str, dtype: int, shape: Sequence[int]) -> bytes:
    dims = b"".join(f_msg(1, f_varint(1, d)) for d in shape)
    tensor_t = f_varint(1, dtype) + f_msg(2, dims)
    type_p = f_msg(1, tensor_t)
    return f_str(1, name) + f_msg(2, type_p)


def graph_proto(nodes: List[bytes], name: str, initializers: List[bytes],
                inputs: List[bytes], outputs: List[bytes]) -> bytes:
    body = b"".join(f_msg(1, n) for n in nodes)
    body += f_str(2, name)
    body += b"".join(f_msg(5, t) for t in initializers)
    body += b"".join(f_msg(11, i) for i in inputs)
    body += b"".join(f_msg(12, o) for o in outputs)
    return body


def model_proto(graph: bytes, opset: int = 17,
                producer: str = "paddle_tpu") -> bytes:
    opset_body = f_str(1, "") + f_varint(2, opset)
    body = f_varint(1, 8)                      # ir_version 8
    body += f_str(2, producer)
    body += f_str(3, "0.1")
    body += f_msg(7, graph)
    body += f_msg(8, opset_body)
    return body


# ---------------------------------------------------------------------------
# independent reader (for tests; subset decode)
# ---------------------------------------------------------------------------

def _read_varint(buf: memoryview, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _fields(buf: memoryview):
    """Yield (field, wire, value) over a message body."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = bytes(buf[pos:pos + 4])
            pos += 4
        elif wire == 1:
            v = bytes(buf[pos:pos + 8])
            pos += 8
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, v


def parse_model(data: bytes) -> dict:
    """Decode the subset we emit: returns {opset, producer, graph:
    {nodes: [{op_type, inputs, outputs, attrs}], initializers:
    [(name, dtype, shape, array)], inputs: [names], outputs: [names]}}."""
    model = {"producer": None, "opset": None, "graph": None}
    for field, _, v in _fields(memoryview(data)):
        if field == 2:
            model["producer"] = bytes(v).decode()
        elif field == 8:
            for f2, _, v2 in _fields(v):
                if f2 == 2:
                    model["opset"] = v2
        elif field == 7:
            g = {"nodes": [], "initializers": [], "inputs": [],
                 "outputs": [], "name": None}
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    node = {"op_type": None, "inputs": [], "outputs": [],
                            "attrs": {}}
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            node["inputs"].append(bytes(v3).decode())
                        elif f3 == 2:
                            node["outputs"].append(bytes(v3).decode())
                        elif f3 == 4:
                            node["op_type"] = bytes(v3).decode()
                        elif f3 == 5:
                            aname, aival, aints, astr = None, None, [], None
                            for f4, w4, v4 in _fields(v3):
                                if f4 == 1:
                                    aname = bytes(v4).decode()
                                elif f4 == 3:
                                    aival = v4
                                elif f4 == 4:
                                    astr = bytes(v4).decode()
                                elif f4 == 8:
                                    aints.append(v4)
                            node["attrs"][aname] = (
                                aints if aints
                                else astr if astr is not None else aival)
                    g["nodes"].append(node)
                elif f2 == 2:
                    g["name"] = bytes(v2).decode()
                elif f2 == 5:
                    tname, dims, dt, raw = None, [], None, b""
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            dims.append(v3)
                        elif f3 == 2:
                            dt = v3
                        elif f3 == 8:
                            tname = bytes(v3).decode()
                        elif f3 == 9:
                            raw = bytes(v3)
                    g["initializers"].append((tname, dt, dims, raw))
                elif f2 == 11:
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            g["inputs"].append(bytes(v3).decode())
                elif f2 == 12:
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            g["outputs"].append(bytes(v3).decode())
            model["graph"] = g
    return model
