"""paddle.onnx — ONNX export.

Reference parity: python/paddle/onnx/export.py (delegates to the
paddle2onnx converter over the static Program). TPU-native design: the
layer is traced to a jaxpr — the same trace jit/StableHLO export uses —
and lowered primitive-by-primitive to ONNX opset 17, with the protobuf
wire format emitted directly (`_proto.py`; the environment ships no onnx
package, and none is needed to WRITE spec-compliant files). Parameters
become initializers under their state_dict names; constant subgraphs
fold away.

Models using primitives outside the mapped inference set raise with the
primitive named; `paddle_tpu.jit.save` (StableHLO AOT) covers the rest.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=17, **configs):
    """Export `layer` to `path`.onnx (parity: paddle.onnx.export).

    input_spec: list of InputSpec / Tensors / (shape, dtype) pairs.
    Dynamic dims (None/-1) are not supported — pass concrete shapes
    (the reference's converter also requires shapes for most models).
    Returns the saved file path.
    """
    from ._export import export_onnx_bytes
    from ..tensor import Tensor

    if not 13 <= int(opset_version) <= 17:
        raise ValueError(
            f"opset_version {opset_version} is not supported: nodes are "
            "emitted with opset 13-17 signatures (ReduceSum/Squeeze/"
            "Split take axes/sizes as inputs) — pass 13 <= opset <= 17")
    if input_spec is None:
        raise ValueError(
            "paddle.onnx.export needs input_spec (shapes + dtypes) to "
            "trace the model")
    specs = []
    for s in input_spec:
        if isinstance(s, Tensor):
            specs.append((tuple(s.shape), np.dtype(str(s.numpy().dtype))))
            continue
        shape = getattr(s, "shape", None)
        if shape is not None and not isinstance(s, (tuple, list)):
            dtype = getattr(s, "dtype", "float32")
            conc = []
            for d in shape:
                if d is None or d == -1:
                    raise ValueError(
                        "ONNX export requires concrete shapes; got a "
                        f"dynamic dim in {shape} — pass the serving "
                        "shape (rebuild per shape if needed)")
                conc.append(int(d))
            from ..framework.dtype import convert_dtype
            try:
                np_dt = np.dtype(convert_dtype(dtype))
            except Exception:
                np_dt = np.dtype(str(dtype))
            specs.append((tuple(conc), np_dt))
        else:
            shape, dtype = s
            if any(d is None or int(d) < 0 for d in shape):
                raise ValueError(
                    "ONNX export requires concrete shapes; got a "
                    f"dynamic dim in {tuple(shape)} — pass the serving "
                    "shape (rebuild per shape if needed)")
            specs.append((tuple(int(d) for d in shape), np.dtype(dtype)))

    data, _ = export_onnx_bytes(layer, specs, opset_version=opset_version)
    out_path = str(path)
    if not out_path.endswith(".onnx"):
        out_path = out_path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(data)
    return out_path
