"""jaxpr -> ONNX lowering.

Reference parity: python/paddle/onnx/export.py (which shells out to the
paddle2onnx converter over the static Program). TPU-native design: the
model is traced to a jaxpr (the same trace `jit`/StableHLO export uses)
and each primitive maps to an ONNX-17 node; parameters become
initializers with their real state_dict names. Constant subgraphs
(iota masks, rope tables, ...) are folded by evaluating eagerly, so
only data-dependent ops land in the graph.

Supported op set covers the standard inference stack (linear/conv/norm/
attention/activations). Unmapped primitives raise with the primitive
named, pointing at the StableHLO AOT path which supports everything.
"""
from __future__ import annotations

import string
from typing import Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from . import _proto as P


class _Graph:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.counter = 0
        self.const_cache: Dict[bytes, str] = {}

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def add(self, op, inputs, outputs, attrs=()):
        self.nodes.append(P.node_proto(op, inputs, outputs,
                                       name=self.fresh(op.lower()),
                                       attrs=attrs))

    def const(self, arr: np.ndarray, hint="const"):
        arr = np.asarray(arr)
        key = (arr.dtype.str.encode() + str(arr.shape).encode()
               + arr.tobytes())
        if key in self.const_cache:
            return self.const_cache[key]
        name = self.fresh(hint)
        self.initializers.append(P.tensor_proto(name, arr))
        self.const_cache[key] = name
        return name


def _einsum_eq(dn, lhs_ndim, rhs_ndim):
    (lc, rc), (lb, rb) = dn
    letters = iter(string.ascii_lowercase)
    lhs = [None] * lhs_ndim
    rhs = [None] * rhs_ndim
    for i, j in zip(lb, rb):
        c = next(letters)
        lhs[i] = c
        rhs[j] = c
    for i, j in zip(lc, rc):
        c = next(letters)
        lhs[i] = c
        rhs[j] = c
    for i in range(lhs_ndim):
        if lhs[i] is None:
            lhs[i] = next(letters)
    for j in range(rhs_ndim):
        if rhs[j] is None:
            rhs[j] = next(letters)
    out = ([lhs[i] for i in lb]
           + [lhs[i] for i in range(lhs_ndim)
              if i not in set(lb) | set(lc)]
           + [rhs[j] for j in range(rhs_ndim)
              if j not in set(rb) | set(rc)])
    return "".join(lhs) + "," + "".join(rhs) + "->" + "".join(out)


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "rem": "Mod",
    "tanh": "Tanh", "exp": "Exp", "log": "Log", "logistic": "Sigmoid",
    "erf": "Erf", "sqrt": "Sqrt", "neg": "Neg", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil", "sin": "Sin",
    "cos": "Cos",
    "eq": "Equal", "lt": "Less", "gt": "Greater", "ge": "GreaterOrEqual",
    "le": "LessOrEqual", "and": "And", "or": "Or", "not": "Not",
    "xor": "Xor",
}

_ONNX2NP = {P.FLOAT: np.float32, P.DOUBLE: np.float64,
            P.FLOAT16: np.float16, P.INT64: np.int64, P.INT32: np.int32,
            P.INT8: np.int8, P.UINT8: np.uint8, P.BOOL: np.bool_}


class _Lowerer:
    def __init__(self, graph: _Graph):
        self.g = graph
        self.env: Dict = {}     # jax Var -> name (str) or np const

    def read(self, atom):
        from jax._src.core import Literal
        if isinstance(atom, Literal):
            return np.asarray(atom.val)
        return self.env[atom]

    def name_of(self, val, hint="c"):
        """Graph name for a value (materializing constants)."""
        if isinstance(val, str):
            return val
        return self.g.const(np.asarray(val), hint)

    # ------------------------------------------------------------------
    def lower_jaxpr(self, jaxpr, consts, in_names):
        for var, cval in zip(jaxpr.constvars, consts):
            self.env[var] = np.asarray(cval)
        for var, name in zip(jaxpr.invars, in_names):
            self.env[var] = name
        for eqn in jaxpr.eqns:
            self.eqn(eqn)
        return [self.read(o) for o in jaxpr.outvars]

    def eqn(self, eqn):
        prim = eqn.primitive.name
        ins = [self.read(v) for v in eqn.invars]

        # recurse into call-like primitives
        if prim in ("jit", "pjit", "closed_call", "custom_jvp_call",
                    "custom_vjp_call", "remat", "checkpoint",
                    "custom_vjp_call_jaxpr"):
            inner = (eqn.params.get("jaxpr")
                     or eqn.params.get("call_jaxpr")
                     or eqn.params.get("fun_jaxpr"))
            closed = inner if hasattr(inner, "jaxpr") else None
            jx = closed.jaxpr if closed else inner
            consts = closed.consts if closed else []
            sub = _Lowerer(self.g)
            in_names = [i if isinstance(i, str)
                        else np.asarray(i) for i in ins]
            outs = sub.lower_jaxpr(jx, consts, in_names)
            for var, o in zip(eqn.outvars, outs):
                self.env[var] = o
            return

        # constant fold when every input is concrete
        if all(not isinstance(i, str) for i in ins):
            out = eqn.primitive.bind(
                *[jnp.asarray(i) for i in ins], **eqn.params)
            outs = out if eqn.primitive.multiple_results else [out]
            for var, o in zip(eqn.outvars, outs):
                self.env[var] = np.asarray(o)
            return

        handler = getattr(self, f"_p_{prim}", None)
        if handler is None and prim in _ELEMENTWISE:
            handler = self._elementwise
        if handler is None:
            raise NotImplementedError(
                f"ONNX export: primitive '{prim}' has no mapping; use "
                "paddle_tpu.jit.save (StableHLO AOT) for full coverage")
        handler(eqn, ins)

    # ------------------------------------------------------------------
    def _out(self, eqn, idx=0, hint=None):
        name = self.g.fresh(hint or eqn.primitive.name)
        self.env[eqn.outvars[idx]] = name
        return name

    def _elementwise(self, eqn, ins):
        op = _ELEMENTWISE[eqn.primitive.name]
        names = [self.name_of(i) for i in ins]
        self.g.add(op, names, [self._out(eqn)])

    def _p_integer_pow(self, eqn, ins):
        y = np.asarray(float(eqn.params["y"]), np.float32)
        self.g.add("Pow", [self.name_of(ins[0]), self.g.const(y)],
                   [self._out(eqn)])

    def _p_erfc(self, eqn, ins):
        e = self.g.fresh("erf")
        self.g.add("Erf", [self.name_of(ins[0])], [e])
        one = self.g.const(np.asarray(
            1.0, eqn.invars[0].aval.dtype))
        self.g.add("Sub", [one, e], [self._out(eqn)])

    def _p_square(self, eqn, ins):
        x = self.name_of(ins[0])
        self.g.add("Mul", [x, x], [self._out(eqn)])

    def _p_rsqrt(self, eqn, ins):
        s = self.g.fresh("sqrt")
        self.g.add("Sqrt", [self.name_of(ins[0])], [s])
        self.g.add("Reciprocal", [s], [self._out(eqn)])

    def _p_is_finite(self, eqn, ins):
        x = self.name_of(ins[0])
        inf = self.g.fresh("isinf")
        nan = self.g.fresh("isnan")
        either = self.g.fresh("or")
        self.g.add("IsInf", [x], [inf])
        self.g.add("IsNaN", [x], [nan])
        self.g.add("Or", [inf, nan], [either])
        self.g.add("Not", [either], [self._out(eqn)])

    def _p_log1p(self, eqn, ins):
        one = self.g.const(np.asarray(1.0, eqn.invars[0].aval.dtype))
        a = self.g.fresh("add1")
        self.g.add("Add", [self.name_of(ins[0]), one], [a])
        self.g.add("Log", [a], [self._out(eqn)])

    def _p_dot_general(self, eqn, ins):
        eq = _einsum_eq(eqn.params["dimension_numbers"],
                        eqn.invars[0].aval.ndim, eqn.invars[1].aval.ndim)
        self.g.add("Einsum", [self.name_of(i) for i in ins],
                   [self._out(eqn)], attrs=[P.attr_str("equation", eq)])

    def _p_reshape(self, eqn, ins):
        shape = np.asarray(eqn.params["new_sizes"], np.int64)
        self.g.add("Reshape",
                   [self.name_of(ins[0]), self.g.const(shape, "shape")],
                   [self._out(eqn)])

    def _p_transpose(self, eqn, ins):
        self.g.add("Transpose", [self.name_of(ins[0])], [self._out(eqn)],
                   attrs=[P.attr_ints("perm", eqn.params["permutation"])])

    def _p_broadcast_in_dim(self, eqn, ins):
        shape = eqn.params["shape"]
        bdims = eqn.params["broadcast_dimensions"]
        # insert size-1 dims at non-broadcast positions, then Expand
        interim = [1] * len(shape)
        for src, dst in enumerate(bdims):
            interim[dst] = eqn.invars[0].aval.shape[src]
        r = self.g.fresh("bcast_reshape")
        self.g.add("Reshape",
                   [self.name_of(ins[0]),
                    self.g.const(np.asarray(interim, np.int64), "shape")],
                   [r])
        self.g.add("Expand",
                   [r, self.g.const(np.asarray(shape, np.int64), "shape")],
                   [self._out(eqn)])

    def _p_convert_element_type(self, eqn, ins):
        to = P.np_to_onnx_dtype(eqn.params["new_dtype"])
        self.g.add("Cast", [self.name_of(ins[0])], [self._out(eqn)],
                   attrs=[P.attr_int("to", to)])

    def _p_stop_gradient(self, eqn, ins):
        self.g.add("Identity", [self.name_of(ins[0])], [self._out(eqn)])

    def _p_copy(self, eqn, ins):
        self.g.add("Identity", [self.name_of(ins[0])], [self._out(eqn)])

    def _p_select_n(self, eqn, ins):
        if len(ins) != 3:
            raise NotImplementedError(
                "ONNX export: select_n with more than two cases; use "
                "jit.save (StableHLO) instead")
        pred, case_f, case_t = ins
        self.g.add("Where", [self.name_of(pred), self.name_of(case_t),
                             self.name_of(case_f)], [self._out(eqn)])

    def _p_concatenate(self, eqn, ins):
        self.g.add("Concat", [self.name_of(i) for i in ins],
                   [self._out(eqn)],
                   attrs=[P.attr_int("axis", eqn.params["dimension"])])

    def _p_slice(self, eqn, ins):
        starts = np.asarray(eqn.params["start_indices"], np.int64)
        ends = np.asarray(eqn.params["limit_indices"], np.int64)
        strides = eqn.params["strides"]
        axes = np.arange(len(starts), dtype=np.int64)
        inputs = [self.name_of(ins[0]), self.g.const(starts, "starts"),
                  self.g.const(ends, "ends"), self.g.const(axes, "axes")]
        if strides is not None:
            inputs.append(self.g.const(
                np.asarray(strides, np.int64), "steps"))
        self.g.add("Slice", inputs, [self._out(eqn)])

    def _p_squeeze(self, eqn, ins):
        dims = np.asarray(eqn.params["dimensions"], np.int64)
        self.g.add("Squeeze",
                   [self.name_of(ins[0]), self.g.const(dims, "axes")],
                   [self._out(eqn)])

    def _reduce(self, eqn, ins, op, axes_as_input):
        axes = np.asarray(eqn.params["axes"], np.int64)
        out = self._out(eqn)
        if axes_as_input:   # ReduceSum signature since opset 13
            self.g.add(op, [self.name_of(ins[0]),
                            self.g.const(axes, "axes")], [out],
                       attrs=[P.attr_int("keepdims", 0)])
        else:
            self.g.add(op, [self.name_of(ins[0])], [out],
                       attrs=[P.attr_ints("axes", axes.tolist()),
                              P.attr_int("keepdims", 0)])

    def _p_reduce_sum(self, eqn, ins):
        self._reduce(eqn, ins, "ReduceSum", True)

    def _p_reduce_max(self, eqn, ins):
        self._reduce(eqn, ins, "ReduceMax", False)

    def _p_reduce_min(self, eqn, ins):
        self._reduce(eqn, ins, "ReduceMin", False)

    def _p_reduce_and(self, eqn, ins):
        # all() over bool: cast -> ReduceMin -> cast back
        c = self.g.fresh("cast")
        self.g.add("Cast", [self.name_of(ins[0])], [c],
                   attrs=[P.attr_int("to", P.INT32)])
        r = self.g.fresh("rmin")
        axes = np.asarray(eqn.params["axes"], np.int64)
        self.g.add("ReduceMin", [c], [r],
                   attrs=[P.attr_ints("axes", axes.tolist()),
                          P.attr_int("keepdims", 0)])
        self.g.add("Cast", [r], [self._out(eqn)],
                   attrs=[P.attr_int("to", P.BOOL)])

    def _p_argmax(self, eqn, ins):
        axes = eqn.params["axes"]
        out = self._out(eqn)
        a = self.g.fresh("argmax")
        self.g.add("ArgMax", [self.name_of(ins[0])], [a],
                   attrs=[P.attr_int("axis", axes[0]),
                          P.attr_int("keepdims", 0)])
        to = P.np_to_onnx_dtype(eqn.outvars[0].aval.dtype)
        self.g.add("Cast", [a], [out], attrs=[P.attr_int("to", to)])

    def _p_conv_general_dilated(self, eqn, ins):
        p = eqn.params
        dn = p["dimension_numbers"]
        # only the NCHW/OIHW layout jax's lax.conv (and our Conv2D) uses
        if (dn.lhs_spec[0] != 0 or dn.lhs_spec[1] != 1
                or dn.rhs_spec[0] != 0 or dn.rhs_spec[1] != 1):
            raise NotImplementedError(
                "ONNX export: conv layout "
                f"{dn} is not NCHW/OIHW; use jit.save (StableHLO)")
        if p["lhs_dilation"] and any(d != 1 for d in p["lhs_dilation"]):
            raise NotImplementedError(
                "ONNX export: transposed conv (lhs_dilation) is not "
                "mapped; use jit.save (StableHLO)")
        pads_lo = [lo for lo, _ in p["padding"]]
        pads_hi = [hi for _, hi in p["padding"]]
        attrs = [P.attr_ints("strides", p["window_strides"]),
                 P.attr_ints("pads", list(pads_lo) + list(pads_hi)),
                 P.attr_ints("dilations", p["rhs_dilation"]),
                 P.attr_int("group", p["feature_group_count"])]
        self.g.add("Conv", [self.name_of(i) for i in ins],
                   [self._out(eqn)], attrs=attrs)

    def _p_split(self, eqn, ins):
        sizes = np.asarray(eqn.params["sizes"], np.int64)
        axis = int(eqn.params["axis"])
        outs = [self._out(eqn, i, "split") for i in range(len(sizes))]
        self.nodes_split(ins, sizes, axis, outs)

    def nodes_split(self, ins, sizes, axis, outs):
        self.g.nodes.append(P.node_proto(
            "Split", [self.name_of(ins[0]), self.g.const(sizes, "sizes")],
            outs, name=self.g.fresh("split"),
            attrs=[P.attr_int("axis", axis)]))

    def _window_2d(self, eqn):
        p = eqn.params
        wd = p["window_dimensions"]
        ws = p["window_strides"]
        pad = p["padding"]
        if (len(wd) < 3 or wd[0] != 1 or wd[1] != 1
                or p.get("base_dilation") and any(
                    d != 1 for d in p["base_dilation"])):
            raise NotImplementedError(
                "ONNX export: only NCHW spatial pooling windows are "
                "mapped; use jit.save (StableHLO)")
        kernel = list(wd[2:])
        strides = list(ws[2:])
        pads = ([lo for lo, _ in pad[2:]] + [hi for _, hi in pad[2:]])
        return kernel, strides, pads

    def _p_reduce_window_max(self, eqn, ins):
        kernel, strides, pads = self._window_2d(eqn)
        self.g.add("MaxPool", [self.name_of(ins[0])], [self._out(eqn)],
                   attrs=[P.attr_ints("kernel_shape", kernel),
                          P.attr_ints("strides", strides),
                          P.attr_ints("pads", pads)])

    def _p_reduce_window_sum(self, eqn, ins):
        # sum window = AveragePool * window_size (count_include_pad so
        # the divisor is constant)
        kernel, strides, pads = self._window_2d(eqn)
        ap = self.g.fresh("avgpool")
        self.g.add("AveragePool", [self.name_of(ins[0])], [ap],
                   attrs=[P.attr_ints("kernel_shape", kernel),
                          P.attr_ints("strides", strides),
                          P.attr_ints("pads", pads),
                          P.attr_int("count_include_pad", 1)])
        n = float(np.prod(kernel))
        self.g.add("Mul", [ap, self.g.const(np.asarray(
            n, eqn.invars[0].aval.dtype))], [self._out(eqn)])

    _p_reduce_window_add = _p_reduce_window_sum

    def _p_iota(self, eqn, ins):
        # reachable only with data-dependent inputs (never: iota has no
        # inputs so constant folding always handles it)
        raise AssertionError("iota should constant-fold")

    def _p_gather(self, eqn, ins):
        # the embedding-lookup pattern jnp.take/x[ids] produces:
        # collapsed slice on axis 0, index vector over axis 0
        dn = eqn.params["dimension_numbers"]
        op_shape = tuple(eqn.invars[0].aval.shape)
        slice_sizes = tuple(eqn.params["slice_sizes"])
        full_rows = (slice_sizes[:1] == (1,)
                     and slice_sizes[1:] == op_shape[1:])
        if (list(dn.collapsed_slice_dims) == [0]
                and list(dn.start_index_map) == [0] and full_rows):
            idx = self.name_of(ins[1], "indices")
            sq = self.g.fresh("idx_squeeze")
            self.g.add("Squeeze",
                       [idx, self.g.const(
                           np.asarray([-1], np.int64), "axes")], [sq])
            self.g.add("Gather", [self.name_of(ins[0]), sq],
                       [self._out(eqn)], attrs=[P.attr_int("axis", 0)])
            return
        raise NotImplementedError(
            "ONNX export: general lax.gather is not mapped (only "
            "axis-0 embedding lookup); use jit.save (StableHLO)")


def export_onnx_bytes(layer, input_specs, opset_version=17):
    """Trace layer.forward (eval mode) and lower to ONNX ModelProto
    bytes. input_specs: list of (shape, np dtype) with no dynamic dims."""
    from ..jit.bridge import functionalize
    from ..tensor import Tensor

    pure_fn, p_vals, b_vals, p_names, _ = functionalize(layer,
                                                        training=False)
    key = jax.random.key(0)
    examples = [jnp.zeros(s, d) for s, d in input_specs]

    def fwd(params, *xs):
        out, _, _ = pure_fn(params, b_vals, key, *xs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._value if isinstance(o, Tensor) else o
                     for o in outs)

    closed = jax.make_jaxpr(fwd)(p_vals, *examples)

    g = _Graph()
    # params -> initializers under their real state_dict names
    in_names = []
    for name, val in zip(p_names, p_vals):
        arr = np.asarray(val)
        g.initializers.append(P.tensor_proto(name, arr))
        in_names.append(name)
    graph_inputs = []
    for i, (s, d) in enumerate(input_specs):
        nm = f"input_{i}"
        in_names.append(nm)
        graph_inputs.append(P.value_info(
            nm, P.np_to_onnx_dtype(np.dtype(d)), s))

    low = _Lowerer(g)
    outs = low.lower_jaxpr(closed.jaxpr, closed.consts, in_names)

    graph_outputs = []
    out_names = []
    for i, (o, var) in enumerate(zip(outs, closed.jaxpr.outvars)):
        nm = low.name_of(o, "output")
        out_names.append(nm)
        graph_outputs.append(P.value_info(
            nm, P.np_to_onnx_dtype(var.aval.dtype),
            var.aval.shape))

    graph = P.graph_proto(g.nodes, "paddle_tpu_graph", g.initializers,
                          graph_inputs, graph_outputs)
    return P.model_proto(graph, opset=opset_version), out_names
