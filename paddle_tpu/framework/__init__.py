"""framework: dtype/place/random/flags (parity: python/paddle/framework/)."""
from __future__ import annotations

from . import dtype
from .dtype import (
    set_default_dtype, get_default_dtype, convert_dtype, finfo, iinfo,
)
from .place import (
    Place, CPUPlace, TPUPlace, XLAPlace, CUDAPlace, set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
)
from .random import (
    seed, get_rng_state, set_rng_state, get_rng_state_tracker,
    default_generator, next_key,
)
from .flags import set_flags, get_flags, define_flag, flag_value
from .selected_rows import SelectedRows
