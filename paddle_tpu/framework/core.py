"""paddle.base.core compat shim (upstream: the pybind C++ module
paddle/fluid/pybind). Exposes the handful of core symbols legacy user
code touches — places, flags accessors, nccl/cuda predicates — mapped
to the TPU-native equivalents."""
from __future__ import annotations

from .place import CPUPlace, TPUPlace, XLAPlace
from .flags import get_flags, set_flags

CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type=None):
    from ..device import get_all_custom_device_type
    return bool(get_all_custom_device_type())


def get_cuda_device_count():
    import jax
    return sum(1 for d in jax.devices() if d.platform != "cpu")


def globals():  # matches core.globals() flag mapping
    return get_flags(None)


class core:
    """Some code does `from paddle.base import core` then `core.X`; this
    class body re-exports the module surface for that spelling."""
    CPUPlace = CPUPlace
    CUDAPlace = TPUPlace
    XLAPlace = XLAPlace
    is_compiled_with_cuda = staticmethod(is_compiled_with_cuda)
    is_compiled_with_xpu = staticmethod(is_compiled_with_xpu)
    get_cuda_device_count = staticmethod(get_cuda_device_count)
    set_flags = staticmethod(set_flags)
    get_flags = staticmethod(get_flags)
