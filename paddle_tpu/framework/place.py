"""Device/place abstraction.

Reference parity: paddle/phi/common/place.h (phi::Place, CPUPlace, GPUPlace,
CustomPlace) and the north star's `XLAPlace`. On TPU the place maps directly
onto a `jax.Device`; streams/contexts are subsumed by XLA's execution model,
so a Place here is a thin named handle used for `.to()` / `paddle.device`
parity rather than a stream owner.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place: a named device handle."""

    _kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    @property
    def jax_device(self):
        devs = [d for d in jax.devices() if _kind_matches(d, self._kind)]
        if not devs:
            # fall back to host platform
            devs = jax.devices("cpu")
        return devs[self._device_id % len(devs)]

    def __eq__(self, other):
        return (isinstance(other, Place) and self._kind == other._kind
                and self._device_id == other._device_id)

    def __hash__(self):
        return hash((self._kind, self._device_id))

    def __repr__(self):
        return f"Place({self._kind}:{self._device_id})"


def _kind_matches(device, kind: str) -> bool:
    plat = device.platform.lower()
    if kind == "cpu":
        return plat == "cpu"
    if kind in ("tpu", "xla"):
        # under the axon tunnel the platform may be reported differently;
        # treat any non-cpu accelerator as the TPU place
        return plat != "cpu"
    return False


class CPUPlace(Place):
    _kind = "cpu"

    def __repr__(self):
        return "Place(cpu)"


class TPUPlace(Place):
    _kind = "tpu"

    def __repr__(self):
        return f"Place(tpu:{self._device_id})"


# North-star naming: XLAPlace is the Paddle-side name for the TPU device.
XLAPlace = TPUPlace
# CUDAPlace parity shim: on this framework it is the accelerator place.
CUDAPlace = TPUPlace


@functools.lru_cache(maxsize=None)
def _accelerator_available() -> bool:
    return any(d.platform.lower() != "cpu" for d in jax.devices())


_current_place = None


def set_device(device) -> Place:
    """paddle.set_device — accepts 'cpu', 'tpu', 'tpu:0', 'gpu' (alias of the
    accelerator), 'xla'."""
    global _current_place
    _current_place = _parse_place(device)
    return _current_place


def get_device() -> str:
    p = _default_place()
    return f"{p._kind}:{p.get_device_id()}" if p._kind != "cpu" else "cpu"


def _parse_place(device) -> Place:
    if isinstance(device, Place):
        return device
    s = str(device).lower()
    if ":" in s:
        kind, _, idx = s.partition(":")
        idx = int(idx)
    else:
        kind, idx = s, 0
    if kind == "cpu":
        return CPUPlace(idx)
    if kind in ("tpu", "gpu", "xla", "cuda", "xpu"):
        # ported XPU scripts select via set_device('xpu:N') — map to the
        # accelerator place like the XPUPlace class shim
        return TPUPlace(idx)
    raise ValueError(f"unknown device {device!r}")


def _default_place() -> Place:
    if _current_place is not None:
        return _current_place
    return TPUPlace(0) if _accelerator_available() else CPUPlace(0)


def is_compiled_with_cuda() -> bool:  # parity stub
    return False


def is_compiled_with_xpu() -> bool:  # parity stub
    return False


def is_compiled_with_tpu() -> bool:
    return _accelerator_available()


def is_compiled_with_rocm() -> bool:  # parity stub
    return False


class CUDAPinnedPlace(Place):
    """Parity shim: pinned host memory is an explicit-staging CUDA
    concept; on TPU host arrays are staged by the runtime. Behaves as
    the CPU place."""
    _kind = "cpu"

    def __repr__(self):
        return "CUDAPinnedPlace"


class XPUPlace(Place):
    """Parity shim: no XPU in this stack; accepted for ported code and
    mapped to the accelerator place."""
    _kind = "tpu"

    def __repr__(self):
        return f"XPUPlace({self._device_id})"
