"""Deterministic, seeded fault injection — the testability backbone of
the fault-tolerance layer (docs/ROBUSTNESS.md).

Production hardening is only real if every recovery path can be driven
in CI. This module is a process-wide registry of *fault sites*: named
points in the runtime (checkpoint I/O, the trainer loop, the serving
decode loop) that ask ``faults.check("site", step=...)`` whether an
armed fault should fire here. Unarmed, a check is one attribute load
and a ``None`` return — the instrumented paths carry no measurable
overhead.

Faults are armed with spec strings, via ``FLAGS_fault_injection`` (env
``FLAGS_fault_injection=...`` or ``paddle.set_flags``) or directly with
:func:`arm`:

    ckpt_save:step=3:err,nan_loss:step=5,slow_step:every=10:sleep=0.2

Grammar (comma-separated specs; each spec is colon-separated tokens):

    site[:key=value | mode]...

Match keys
    ``step=N`` / ``step=A-B``  match the ``step`` kwarg the site passes
    ``hit=N``                  fire on the Nth check of this site (1-based)
    ``every=N``                fire on every Nth check
    ``times=K``                max fires for this spec (0 = unlimited;
                               default 1, or 0 when ``every``/``prob``
                               is given — those describe recurring
                               faults)
    ``prob=P`` [``seed=S``]    fire with probability P — *deterministic*:
                               the coin is a hash of (seed, site, hit
                               count), so a given spec fires at the same
                               hits in every run
Action modes (bare words; sites interpret them)
    ``err``       raise an IOError at the site (transient I/O failure)
    ``truncate``  torn write: truncate one payload file post-finalize
    ``corrupt``   bitrot: flip a byte in one payload file post-finalize
    ``drop_manifest``  partial write: checkpoint dir without a manifest
    ``nan`` / ``inf``  the observed loss becomes NaN / Inf
    ``sigterm``   deliver SIGTERM to this process (preemption)
    ``sleep=S``   stall the site for S seconds (slow step / wedged decode)
    ``flood``     serving: inflate the apparent queue depth by ``n=K``

Sites instrumented in-tree: ``ckpt_save``, ``ckpt_write``, ``ckpt_slow``
(in ``distributed.checkpoint.VerifiedCheckpointer`` — ``ckpt_slow``
stalls the write pipeline to exercise the async drain), ``nan_loss``,
``slow_step``, ``rank_hang`` (the trainer loop wedges: an alive pid
that stops making progress — the launcher's stale-heartbeat detector's
prey), ``slow_rank`` (a per-step injected sleep on ONE rank of a
multi-rank job: pass ``rank=K`` and the Trainer applies the sleep only
on that rank — the persistent-skew straggler the launcher's
``FleetAggregator`` exists to flag, invisible to the stale-heartbeat
detector because the rank keeps beating), ``rank_slow`` (persistent
*multiplicative* step inflation on one rank: ``rank=K`` targets it,
``factor=F`` scales the measured step work by F — unlike ``slow_rank``'s
fixed sleep this models a degraded host whose slowness tracks the
workload; the mitigation actuator's canonical prey), ``comm_degraded``
(inflated per-byte collective latency through the ``collective.py``
facade: ``rank=K`` pays ``per_mb=S`` seconds per MiB inside the
``comm.wait`` span, so the degradation presents as comm-wait skew in
the fleet view — a slow NIC, not a slow core), ``sigterm`` (in
``trainer.Trainer``), ``decode_wedge``,
``serve_flood`` (in ``inference.ContinuousBatchingPredictor``),
``collective_stall`` (``distributed.collective`` sync deadline — holds
buffer readiness false so the collective watchdog trips),
``heartbeat_stall`` (``observability.RankHeartbeat`` stops writing
while the process stays alive — the silent-rank signature), and
``handoff_corrupt`` (``serving.router`` flips one payload byte in a
disaggregated KV span *before* import — the checksum fence must reject
it and the request must re-prefill from scratch, bitwise-identically,
instead of decoding from corrupt pages). Sites
are free-form strings — new subsystems add theirs without touching this
module.

Every fired fault increments the ``robustness.faults_injected``
counter (labels: site, mode) and is recorded in :func:`events` for
test assertions.
"""
from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["FaultSpec", "FaultAction", "FaultRegistry", "arm", "disarm",
           "check", "armed", "events", "get_registry"]

_MODES = ("err", "truncate", "corrupt", "drop_manifest", "nan", "inf",
          "sigterm", "sleep", "flood", "drop")

# a bare site with no explicit mode gets its natural failure kind
_DEFAULT_MODES = {
    "ckpt_save": "err", "ckpt_write": "truncate", "nan_loss": "nan",
    "slow_step": "sleep", "sigterm": "sigterm", "decode_wedge": "sleep",
    "serve_flood": "flood", "rank_hang": "sleep", "slow_rank": "sleep",
    "collective_stall": "sleep", "ckpt_slow": "sleep",
    "heartbeat_stall": "sleep", "rank_slow": "sleep",
    "comm_degraded": "sleep", "handoff_corrupt": "corrupt",
}


@dataclass
class FaultSpec:
    """One parsed fault spec: where it fires, when, and what it does."""
    site: str
    mode: str
    step_lo: Optional[int] = None
    step_hi: Optional[int] = None
    hit: Optional[int] = None
    every: Optional[int] = None
    times: int = 1              # 0 = unlimited
    prob: Optional[float] = None
    seed: int = 0
    params: Dict[str, float] = field(default_factory=dict)
    fired: int = 0
    text: str = ""

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        toks = [t for t in text.strip().split(":") if t]
        if not toks:
            raise ValueError(f"empty fault spec in {text!r}")
        spec = cls(site=toks[0], mode="", text=text.strip())
        times_explicit = False
        for tok in toks[1:]:
            if "=" in tok:
                k, v = tok.split("=", 1)
                if k == "step":
                    if "-" in v:
                        lo, hi = v.split("-", 1)
                        spec.step_lo, spec.step_hi = int(lo), int(hi)
                    else:
                        spec.step_lo = spec.step_hi = int(v)
                elif k == "hit":
                    spec.hit = int(v)
                elif k == "every":
                    spec.every = int(v)
                elif k == "times":
                    spec.times = int(v)
                    times_explicit = True
                elif k == "prob":
                    spec.prob = float(v)
                elif k == "seed":
                    spec.seed = int(v)
                elif k == "sleep":
                    spec.mode = "sleep"
                    spec.params["sleep"] = float(v)
                else:
                    spec.params[k] = float(v)
            elif tok in _MODES:
                spec.mode = tok
            else:
                raise ValueError(
                    f"unknown token {tok!r} in fault spec {text!r} "
                    f"(modes: {', '.join(_MODES)})")
        if not spec.mode:
            spec.mode = _DEFAULT_MODES.get(spec.site, "err")
        if not times_explicit and (spec.every is not None
                                   or spec.prob is not None):
            spec.times = 0  # every=/prob= describe RECURRING faults
        return spec

    def _coin(self, hit_count: int) -> bool:
        """Deterministic Bernoulli draw keyed by (seed, site, hit)."""
        h = hashlib.sha256(
            f"{self.seed}:{self.site}:{hit_count}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64 < self.prob

    def matches(self, step: Optional[int], hit_count: int) -> bool:
        if self.times and self.fired >= self.times:
            return False
        if self.step_lo is not None:
            if step is None or not (self.step_lo <= step <= self.step_hi):
                return False
        if self.hit is not None and hit_count != self.hit:
            return False
        if self.every is not None and hit_count % self.every != 0:
            return False
        if self.prob is not None and not self._coin(hit_count):
            return False
        return True


@dataclass
class FaultAction:
    """What a site should do: returned by check() when a spec fires."""
    site: str
    mode: str
    params: Dict[str, float]
    spec: FaultSpec


class FaultRegistry:
    """Process-wide armed-fault state. One instance (module-level); the
    ``FLAGS_fault_injection`` on_change hook keeps it in sync with the
    flag so env arming works before any subsystem imports."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: List[FaultSpec] = []
        self._hits: Dict[str, int] = {}
        self._events: List[dict] = []

    def arm(self, spec_text: Optional[str]):
        """Replace the armed spec set (empty/None disarms). Hit and
        fired counts reset so arming is a clean experiment boundary."""
        specs = []
        for part in (spec_text or "").split(","):
            part = part.strip()
            if part:
                specs.append(FaultSpec.parse(part))
        with self._lock:
            self._specs = specs
            self._hits = {}
            self._events = []

    def disarm(self):
        self.arm(None)

    @property
    def armed(self) -> bool:
        return bool(self._specs)

    def check(self, site: str, step: Optional[int] = None) \
            -> Optional[FaultAction]:
        """Ask whether an armed fault fires at this site now. Counts
        the check (hit) even when nothing fires, so hit-based specs are
        deterministic; near-zero cost while disarmed."""
        if not self._specs:          # fast path: nothing armed
            return None
        with self._lock:
            h = self._hits.get(site, 0) + 1
            self._hits[site] = h
            for spec in self._specs:
                if spec.site != site or not spec.matches(step, h):
                    continue
                spec.fired += 1
                act = FaultAction(site=site, mode=spec.mode,
                                  params=dict(spec.params), spec=spec)
                self._events.append({"site": site, "mode": spec.mode,
                                     "step": step, "hit": h,
                                     "spec": spec.text})
                break
            else:
                return None
        # record outside the lock: the metrics layer has its own
        try:
            from ..observability import metrics as _obsm
            _obsm.counter("robustness.faults_injected").inc(
                site=site, mode=act.mode)
        except Exception:
            pass
        return act

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)


_registry = FaultRegistry()


def get_registry() -> FaultRegistry:
    return _registry


def arm(spec_text: Optional[str]):
    _registry.arm(spec_text)


def disarm():
    _registry.disarm()


def armed() -> bool:
    return _registry.armed


def check(site: str, step: Optional[int] = None) -> Optional[FaultAction]:
    return _registry.check(site, step=step)


def events() -> List[dict]:
    return _registry.events()
