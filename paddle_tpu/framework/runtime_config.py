"""RuntimeConfig — the typed, versioned performance-knob surface.

Before this module every tunable lived somewhere different: chunked
prefill in ``FLAGS_serve_prefill_chunk_tokens``, the decode watchdog in
``FLAGS_serve_decode_watchdog_s``, gradient bucketing in
``FLAGS_grad_bucket_bytes`` / ``FLAGS_quantized_grad_comm``, pool and
queue sizing in ``ContinuousBatchingPredictor`` ctor args, the WFS
quantum hardcoded in ``serving/scheduler.py``. Nothing could version,
hash, diff, or ship that state as one artifact — which is exactly what
telemetry-driven auto-tuning (``tools/autotune.py``) and per-bundle
deployment (``inference/aot``) need.

One object now owns them:

- ``RuntimeConfig`` is a frozen dataclass with a schema ``version``;
  ``to_dict``/``from_dict`` round-trip it as plain JSON and
  ``config_hash()`` is a stable SHA-256 over the canonical form — the
  hash joins the AOT bundle fingerprint so a tuning proposal ships as a
  versioned deploy artifact (docs/DEPLOYMENT.md).
- ``from_flags()`` is the LEGACY bridge: a config whose migrated knobs
  come from the FLAGS registry, so every existing call site keeps its
  exact behavior when no config is passed. This module is the ONLY
  place allowed to read those flags directly — graft-lint GL106
  enforces it (docs/STATIC_ANALYSIS.md).
- ``diff(other)`` names the fields two configs disagree on; the AOT
  warm-start path uses it to emit ``aot.config_drift`` telemetry when
  the bundle's baked config and the ambient (FLAGS/env) config diverge.

Consumers: ``ContinuousBatchingPredictor`` (geometry, buckets, chunked
prefill, queue/shed, watchdog, WFS quantum), ``DistTrainStep`` /
``collective.GradBucketer`` (gradient comm), ``inference.aot``
(manifest + invalidation). Per-tenant and per-role (disaggregated
prefill/decode) configs layer on top of this object.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = ["RuntimeConfig", "CONFIG_VERSION", "config_hash",
           "MIGRATED_FLAG_KNOBS", "COMPILED_FIELDS", "ROLE_OVERLAYS",
           "SERVE_ROLES"]

CONFIG_VERSION = 1

# Fields that shape what an AOT bundle actually compiles/calibrates
# (program shapes, paged-pool layout, the admission bucket table, the
# chunk buckets). Only a disagreement HERE invalidates a bundle at
# warm start; the remaining fields are runtime-only knobs that may
# differ per replica/deployment without destroying the shared bundle
# (docs/DEPLOYMENT.md "Runtime config").
COMPILED_FIELDS = frozenset({
    "max_batch_size", "page_size", "num_pages", "max_seq_len",
    "prompt_buckets", "prefill_chunk_tokens",
    # speculative decoding + on-device sampling are PROGRAM VARIANTS:
    # the spec-verify span bucket derives from spec_draft_tokens and
    # sampling_enabled switches the decode program to the
    # batched-operand sampling variant — different executables either
    # way (spec_ngram_max is host-side drafting policy: runtime-only)
    "spec_draft_tokens", "sampling_enabled",
    # tensor-parallel serving degree: the GSPMD partitioning (weights
    # over the 'model' mesh axis, KV pages over KV heads) is compiled
    # INTO every executable — a bundle built at one degree is
    # meaningless at another (the serve-path `topology` invalidation)
    "tp_degree",
})

# FLAGS_* knobs that migrated INTO RuntimeConfig: reading any of these
# via flag_value()/get_flags outside this module is a graft-lint GL106
# finding — the knob must flow through a RuntimeConfig instead, or the
# bundle-baked config and the running config silently diverge.
MIGRATED_FLAG_KNOBS = {
    "serve_prefill_chunk_tokens": "prefill_chunk_tokens",
    "serve_decode_watchdog_s": "decode_watchdog_s",
    "serve_spec_draft_tokens": "spec_draft_tokens",
    "serve_spec_ngram_max": "spec_ngram_max",
    "serve_sampling": "sampling_enabled",
    "serve_tp_degree": "tp_degree",
    "grad_bucket_bytes": "grad_bucket_bytes",
    "quantized_grad_comm": "quantized_grad_comm",
    "serve_role": "serve_role",
}

# Disaggregated serving roles (docs/SERVING.md "Disaggregated
# prefill/decode"). "unified" is the historical do-everything replica
# and stays the default everywhere.
SERVE_ROLES = ("unified", "prefill", "decode")

# Per-role RuntimeConfig overlays: the field deltas `for_role()` lays
# over a base config. Prefill replicas never run the spec/sampling
# decode programs (they stop at the first token), decode replicas
# never chunk-ingest a prompt (they resume from an imported span) —
# dropping those program variants is what shrinks the per-role AOT
# bundle and its cold start.
ROLE_OVERLAYS = {
    "unified": {},
    "prefill": {"spec_draft_tokens": 0, "sampling_enabled": False},
    "decode": {"prefill_chunk_tokens": 0},
}


@dataclass(frozen=True)
class RuntimeConfig:
    """Every field is a plain JSON-able scalar/tuple so the config can
    live in a bundle manifest byte-for-byte. Field defaults equal the
    historical ctor/flag defaults — ``RuntimeConfig()`` reproduces the
    pre-migration behavior exactly (``from_flags()`` additionally folds
    in FLAGS overrides)."""

    version: int = CONFIG_VERSION

    # -- serving geometry (compiled into AOT executables) ---------------
    max_batch_size: int = 4
    page_size: int = 16
    num_pages: Optional[int] = None        # None: B * pages_per_seq
    max_seq_len: int = 512
    # admission prompt-length buckets; () = power-of-two auto bucketing
    # (the historical LLMPredictor._bucket behavior)
    prompt_buckets: Tuple[int, ...] = ()
    prefill_chunk_tokens: int = 0          # 0 = monolithic prefill
    # speculative decoding: max drafted tokens per verify step (the
    # compiled verify span is spec_draft_tokens + 1 wide); 0 = off.
    # sampling_enabled switches decode to the batched-operand sampling
    # program (per-request temperature/top-k/top-p/seed; temperature 0
    # is greedy, token-identical to the argmax program). Both are
    # COMPILED_FIELDS — program variants, not runtime knobs.
    spec_draft_tokens: int = 0
    # prompt-lookup drafting: longest suffix n-gram matched against the
    # request's own prompt+generation history (runtime-only policy)
    spec_ngram_max: int = 3
    sampling_enabled: bool = False
    # tensor-parallel serving: one replica spans tp_degree devices —
    # weights NamedSharding'ed over the 'model' mesh axis, PagedKVPool
    # pages sharded over KV heads, every serve program GSPMD-partitioned
    # (docs/SERVING.md "Tensor-parallel replicas"). 1 = single-device.
    tp_degree: int = 1
    # disaggregated serving role of the replica this config drives:
    # "unified" (prefill+decode, the historical default), "prefill"
    # (fills pages, hands off at first token), or "decode" (resumes
    # from an imported KV span). NOT a COMPILED_FIELD — the AOT layer
    # bakes the role into the bundle fingerprint next to topology and
    # invalidates with its own reason ("role") so per-role bundle sets
    # stay distinguishable from generic config drift.
    serve_role: str = "unified"

    # -- serving robustness / fairness (runtime-only) --------------------
    max_queue: Optional[int] = None        # None = unbounded backlog
    shed_policy: str = "newest"
    decode_watchdog_s: float = 0.0         # 0 = disabled
    wfs_quantum: float = 64.0              # WeightedFairScheduler grant

    # -- training comm ---------------------------------------------------
    grad_bucket_bytes: int = 32 * 1024 * 1024
    quantized_grad_comm: bool = False
    # ZeRO sharding stage for DistTrainStep when the caller does not pin
    # sharding_stage explicitly: 0 = plain DP, 1 = opt-state sharding
    # (weight-update sharding), 2 = + persistent grad shards, 3 = params
    # sharded (FSDP). Runtime-only: training-step bundles record it in
    # their own topology fingerprint (hybrid/aot.py), so it does not
    # join COMPILED_FIELDS and never invalidates a SERVING bundle.
    zero_stage: int = 0

    def __post_init__(self):
        if self.version != CONFIG_VERSION:
            raise ValueError(
                f"RuntimeConfig schema version {self.version} is not "
                f"supported (this build speaks version {CONFIG_VERSION})")
        if self.shed_policy not in ("newest", "oldest"):
            raise ValueError(
                f"shed_policy must be 'newest' or 'oldest', got "
                f"{self.shed_policy!r}")
        if self.page_size <= 0 or self.max_batch_size <= 0 \
                or self.max_seq_len <= 0:
            raise ValueError("geometry fields must be positive")
        if self.zero_stage not in (0, 1, 2, 3):
            raise ValueError(
                f"zero_stage must be 0..3, got {self.zero_stage!r}")
        if self.spec_draft_tokens < 0 or self.spec_ngram_max < 1:
            raise ValueError(
                "spec_draft_tokens must be >= 0 and spec_ngram_max "
                f">= 1, got {self.spec_draft_tokens!r}/"
                f"{self.spec_ngram_max!r}")
        if self.tp_degree < 1:
            raise ValueError(
                f"tp_degree must be >= 1, got {self.tp_degree!r}")
        if self.serve_role not in SERVE_ROLES:
            raise ValueError(
                f"serve_role must be one of {SERVE_ROLES}, got "
                f"{self.serve_role!r}")
        # normalize buckets: sorted unique ints (hash stability)
        object.__setattr__(
            self, "prompt_buckets",
            tuple(sorted({int(b) for b in self.prompt_buckets})))

    # ------------------------------------------------------------ flags --
    @classmethod
    def from_flags(cls) -> "RuntimeConfig":
        """The FLAGS-sourced default config — the legacy bridge every
        consumer falls back to when no explicit config is passed, so
        flag-driven deployments keep working unchanged. The only
        sanctioned direct read of the migrated knobs (GL106)."""
        from .flags import flag_value

        def _fv(name, default):
            try:
                return flag_value(name)
            except KeyError:
                return default

        return cls(
            prefill_chunk_tokens=int(
                _fv("serve_prefill_chunk_tokens", 0)),
            decode_watchdog_s=float(_fv("serve_decode_watchdog_s", 0.0)),
            spec_draft_tokens=int(_fv("serve_spec_draft_tokens", 0)),
            spec_ngram_max=int(_fv("serve_spec_ngram_max", 3)),
            sampling_enabled=bool(_fv("serve_sampling", False)),
            tp_degree=int(_fv("serve_tp_degree", 1)),
            grad_bucket_bytes=int(_fv("grad_bucket_bytes", 32 << 20)),
            quantized_grad_comm=bool(_fv("quantized_grad_comm", False)),
            serve_role=str(_fv("serve_role", "unified")),
        )

    # -------------------------------------------------------------- role --
    def for_role(self, role: str, **extra) -> "RuntimeConfig":
        """The per-role specialization of this config: lays the
        ``ROLE_OVERLAYS[role]`` field deltas (and any explicit ``extra``
        overrides, which win) over the base, with ``serve_role`` pinned
        to ``role``. ``for_role("unified")`` is the identity apart from
        the pin — a unified fleet keeps its exact historical config."""
        if role not in SERVE_ROLES:
            raise ValueError(
                f"serve_role must be one of {SERVE_ROLES}, got {role!r}")
        kw = dict(ROLE_OVERLAYS[role])
        kw.update(extra)
        kw["serve_role"] = role
        return self.replace(**kw)

    # -------------------------------------------------------- serialize --
    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["prompt_buckets"] = list(self.prompt_buckets)
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "RuntimeConfig":
        """Inverse of ``to_dict``. Unknown keys are rejected — a manifest
        written by a NEWER schema must not silently load with half its
        knobs dropped (the version gate catches the honest case; this
        catches a hand-edited manifest)."""
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown RuntimeConfig field(s): {sorted(unknown)}")
        kw = dict(d)
        if "prompt_buckets" in kw and kw["prompt_buckets"] is not None:
            kw["prompt_buckets"] = tuple(kw["prompt_buckets"])
        return cls(**kw)

    def replace(self, **kw) -> "RuntimeConfig":
        return dataclasses.replace(self, **kw)

    # ------------------------------------------------------------- hash --
    def config_hash(self) -> str:
        return config_hash(self.to_dict())

    def diff(self, other: "RuntimeConfig") -> Dict[str, tuple]:
        """{field: (self_value, other_value)} for every disagreement —
        the drift surface ``aot.config_drift`` telemetry reports."""
        a, b = self.to_dict(), other.to_dict()
        return {k: (a[k], b[k]) for k in a if a[k] != b[k]}

    # ---------------------------------------------------------- buckets --
    def prompt_bucket(self, n: int) -> int:
        """Admission bucket for a prompt of length ``n``: the smallest
        configured bucket covering it, else the historical power-of-two
        fallback (also used past the end of a configured table, so a
        table tuned on observed traffic never rejects an outlier)."""
        for b in self.prompt_buckets:
            if b >= n:
                return b
        b = 8
        while b < n:
            b *= 2
        return b


def config_hash(d: Dict) -> str:
    """SHA-256 of the canonical JSON form. Stable across processes and
    import orders; ``tools/autotune.py`` and ``tools/aot_report.py``
    reimplement this byte-for-byte (they must run without importing
    paddle_tpu/jax — parity is pinned by tests/test_autotune.py)."""
    return hashlib.sha256(
        json.dumps(d, sort_keys=True, separators=(",", ":"),
                   default=str).encode()).hexdigest()
