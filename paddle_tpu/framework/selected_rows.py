"""SelectedRows (parity: paddle/fluid/framework/selected_rows.h and the
python surface paddle.base.libpaddle.SelectedRows).

The reference uses SelectedRows as the sparse-gradient container for
embedding lookups (rows = touched ids, value = their gradient slices).
TPU-native stance: XLA scatters dense gradients for embeddings (the MXU
prefers dense math, and jit fuses the scatter), so the framework never
PRODUCES SelectedRows — this class exists for API compatibility (code
that constructs/merges them, e.g. custom optimizers ported from the
reference) and converts losslessly to/from dense tensors.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["SelectedRows"]


class SelectedRows:
    def __init__(self, rows=None, height: int = 0):
        self._rows = [int(r) for r in (rows or [])]
        self._height = int(height)
        self._value: Tensor = Tensor(jnp.zeros((0,)))

    # -- reference surface -------------------------------------------------
    def rows(self):
        return list(self._rows)

    def set_rows(self, rows):
        self._rows = [int(r) for r in rows]

    def height(self):
        return self._height

    def set_height(self, h):
        self._height = int(h)

    def get_tensor(self) -> Tensor:
        return self._value

    def set_tensor(self, value):
        self._value = value if isinstance(value, Tensor) else Tensor(
            jnp.asarray(value))

    def sync_index(self):  # reference no-op parity
        pass

    def has_rows(self) -> bool:
        return bool(self._rows)

    # -- conversions -------------------------------------------------------
    def to_dense(self) -> Tensor:
        """Scatter-add the row slices into a dense [height, ...] tensor
        (duplicate rows accumulate, matching the reference's merge_add)."""
        val = self._value._value
        shape = (self._height,) + tuple(val.shape[1:])
        dense = jnp.zeros(shape, val.dtype)
        if self._rows:
            idx = jnp.asarray(np.asarray(self._rows, np.int32))
            dense = dense.at[idx].add(val)
        return Tensor(dense)

    @staticmethod
    def from_dense(tensor, rows=None) -> "SelectedRows":
        """Build from a dense tensor, keeping only `rows` (default: rows
        with any non-zero entry)."""
        val = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(
            tensor)
        if rows is None:
            flat = np.asarray(jnp.any(
                val.reshape(val.shape[0], -1) != 0, axis=1))
            rows = [int(i) for i in np.nonzero(flat)[0]]
        rows = [int(r) for r in rows]  # accept arrays/tensors
        sr = SelectedRows(rows=rows, height=val.shape[0])
        idx = jnp.asarray(np.asarray(rows, np.int32)) if len(rows) else \
            jnp.zeros((0,), jnp.int32)
        sr.set_tensor(Tensor(val[idx]))
        return sr

    def __repr__(self):
        return (f"SelectedRows(height={self._height}, "
                f"rows={self._rows[:8]}{'...' if len(self._rows) > 8 else ''}, "
                f"value_shape={list(self._value.shape)})")
