"""Shared artifact-integrity helpers: SHA-256 digests + atomic writes.

One implementation for every on-disk artifact store in the framework —
`distributed.checkpoint.VerifiedCheckpointer` (verified training
checkpoints) and `inference.aot` (serialized engine bundles) both write
through these helpers, so the durability contract is stated once:

- **Digests.** `sha256_file` / `sha256_bytes` produce the manifest
  digests; a reader that re-hashes against the manifest detects
  truncation, bitrot, and partial writes instead of loading them.
- **Atomicity.** `atomic_write_bytes` / `atomic_write_json` write to a
  temp name in the destination directory and `os.replace` into place;
  `replace_dir` does the same for a fully-staged directory. A crash
  mid-write never leaves a half-artifact under the final name.
- **Orphan sweep.** `sweep_tmp` removes THIS process's leftover temp
  files/dirs from earlier failed attempts (other pids may have writes
  in flight under their own suffix — never touch those).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Optional

__all__ = [
    "sha256_bytes", "sha256_file", "atomic_write_bytes",
    "atomic_write_json", "replace_dir", "tmp_name", "sweep_tmp",
]

_CHUNK = 1 << 20


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(_CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def tmp_name(final_path: str, kind: str = "tmp") -> str:
    """Temp sibling of `final_path`, unique to this pid: same
    filesystem (so os.replace is atomic) and sweepable by suffix."""
    d, base = os.path.split(os.path.abspath(final_path))
    return os.path.join(d, f".{kind}-{base}-{os.getpid()}")


def sweep_tmp(directory: str, kind: str = "tmp"):
    """Remove THIS process's orphaned temp files/dirs in `directory`
    (earlier failed attempts). Other pids' temps are left alone: a
    sibling rank sharing the directory may have a write in flight, and
    deleting it would turn one transient fault into a cross-process
    failure. Foreign orphans cost disk, not correctness."""
    suffix = f"-{os.getpid()}"
    prefix = f".{kind}-"
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for n in names:
        if n.startswith(prefix) and n.endswith(suffix):
            p = os.path.join(directory, n)
            try:
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
                else:
                    os.unlink(p)
            except OSError:
                pass


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Write bytes durably-atomically: temp sibling + os.replace.
    Returns the SHA-256 hex digest of `data`."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = tmp_name(path)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return sha256_bytes(data)


def atomic_write_json(path: str, obj) -> str:
    """JSON-serialize `obj` and atomically write it; returns the
    digest of the serialized bytes."""
    return atomic_write_bytes(path, json.dumps(obj).encode())


def replace_dir(tmp_dir: str, final_dir: str,
                remove_existing: bool = True) -> str:
    """Atomically promote a fully-staged temp directory to its final
    name (the VerifiedCheckpointer/engine-bundle commit step). An
    existing final dir is removed first when `remove_existing`."""
    final_dir = os.path.abspath(final_dir)
    if remove_existing and os.path.exists(final_dir):
        shutil.rmtree(final_dir)
    os.replace(tmp_dir, final_dir)
    return final_dir


def read_json(path: str) -> Optional[dict]:
    """Best-effort JSON read: None when missing/unparseable (callers
    treat that as 'artifact absent / invalid', not an exception)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
