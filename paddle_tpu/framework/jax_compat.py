"""Version-bridging shims over jax APIs that moved between releases.

The engine targets the modern surface (``jax.shard_map`` with
``axis_names``/``check_vma``, ``jax.lax.pcast``); older jax releases only
ship ``jax.experimental.shard_map.shard_map`` (``auto``/``check_rep``)
and have no varying-manual-axis (vma) type system at all. Every caller
imports from here so the whole codebase degrades together instead of
each site growing its own try/except ladder.
"""
from __future__ import annotations

import jax


class ShardMapUnsupported(NotImplementedError):
    """The requested shard_map lowering does not exist on this jax
    release. Raised ONLY by :func:`shard_map` for the partial-manual
    case (manual over a subset of the >1-sized mesh axes) on jax
    without the top-level ``jax.shard_map``. Callers/tests that want
    to degrade gracefully must catch exactly this type — catching bare
    ``NotImplementedError`` would also swallow unrelated missing
    features and mask real regressions (tests/test_pipeline.py
    ``_partial_manual_or_skip``)."""


def _modern_shard_map():
    """jax >= 0.8 top-level alias, or None on older releases."""
    sm = getattr(jax, "shard_map", None)
    # jax 0.4.x exposes a deprecation stub raising AttributeError from
    # module __getattr__, so getattr alone is enough of a probe
    return sm


def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
              check_vma=True):
    """``jax.shard_map`` facade with the modern keyword surface.

    axis_names: the axes manualized by this shard_map (None = all mesh
    axes). On old jax, size-1 non-manual axes are folded into the
    manual set (identical semantics), genuinely-partial regions raise
    (old shard_map's partial-auto lowering crashes XLA), and
    replication checking is forced off (see inline note).
    """
    sm = _modern_shard_map()
    if sm is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    mesh_axes = set(getattr(mesh, "axis_names", ()) or ())
    manual = mesh_axes if axis_names is None else set(axis_names)
    # size-1 axes are identical manual or auto (there is nothing to
    # shard); manualizing them keeps hybrid meshes like
    # build_mesh(pp=2) — which names every axis at degree 1 — on the
    # well-supported full-manual path of old shard_map
    sizes = dict(getattr(mesh, "shape", {}) or {})
    auto = frozenset(a for a in mesh_axes - manual if sizes.get(a, 1) > 1)
    if auto:
        # old shard_map's partial-auto lowering is broken beyond repair
        # (SPMD partitioner CHECK-fails and aborts the process on the
        # scan+ppermute schedules); fail like an ordinary python error
        # so callers/tests see a diagnosable exception instead of a
        # crashed interpreter
        raise ShardMapUnsupported(
            "partial-manual shard_map (manual "
            f"{sorted(manual)} / auto {sorted(auto)}) is unsupported on "
            "this jax: use jax >= 0.8 (jax.shard_map), or keep the "
            "region fully manual by collapsing the auto axes to size 1")
    # check_rep stays OFF on old jax regardless of check_vma: its
    # replication oracle predates the varying-manual-axis types
    # (lax.pcast is a no-op here, see pcast below), so scan carries
    # that are legitimately device-varying — the pipeline schedules'
    # ppermute rings — cannot be marked as such and would be rejected
    # as replication violations. The modern path keeps full checking.
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def x64_safe_shard_map_trace():
    """Context for tracing jitted programs that contain a shard_map'd
    scan. Under jax_enable_x64 the old shard_map's full-to-shard
    transpose emits dynamic-update-slices whose partition-offset
    arithmetic mixes s64/s32 and fails HLO verification after SPMD
    partitioning; tracing with x64 off keeps every index s32 and
    sidesteps the bug. On jax with the modern shard_map this is a
    no-op."""
    import contextlib
    if _modern_shard_map() is not None:
        return contextlib.nullcontext()
    from jax.experimental import disable_x64
    return disable_x64()


def narrow_x64_leaves(tree):
    """Cast 64-bit array leaves to their 32-bit counterparts, leaves of
    other dtypes (including PRNG keys) pass through. Companion to
    x64_safe_shard_map_trace: tracing with x64 off canonicalizes avals
    to 32 bits, so concrete 64-bit inputs (e.g. int64 token ids from
    to_tensor under global x64) must be narrowed before the call or the
    lowered module fails dtype verification. No-op on jax with the
    modern shard_map."""
    if _modern_shard_map() is not None:
        return tree
    import jax.numpy as jnp
    import numpy as np

    narrow = {np.dtype(np.int64): jnp.int32,
              np.dtype(np.uint64): jnp.uint32,
              np.dtype(np.float64): jnp.float32,
              np.dtype(np.complex128): jnp.complex64}

    def leaf(a):
        dt = getattr(a, "dtype", None)
        try:
            to = narrow.get(np.dtype(dt)) if dt is not None else None
        except TypeError:  # extended dtypes (PRNG keys)
            return a
        return a.astype(to) if to is not None else a

    return jax.tree_util.tree_map(leaf, tree)


def pcast(val, axes, to="varying"):
    """``jax.lax.pcast`` when the vma type system exists; identity
    otherwise (pre-vma jax has no varying/invariant distinction, so the
    cast is meaningless there and values flow through unchanged)."""
    if isinstance(axes, str):
        axes = (axes,)
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return val
    return fn(val, tuple(axes), to=to)
