"""Typed global flag registry.

Reference parity: paddle/phi/core/flags.cc (gflags-style FLAGS_* registry,
env-settable) and python/paddle/base/framework.py::set_flags/get_flags.
Flags front JAX config + our framework knobs. Each flag has a type, default,
help string, and env override (FLAGS_<name>).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    on_change: Optional[Callable[[Any], None]] = None
    value: Any = None


_REGISTRY: Dict[str, _Flag] = {}


def _coerce(ty, raw):
    if ty is bool:
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return ty(raw)


def _native_mirror(name, ty, value, help_=""):
    """Mirror a flag into the native registry (csrc/flags.cc) so native
    components see framework flag state. Deferred: no-op until something
    actually loads the native lib (so `import paddle_tpu` never triggers a
    compile); load() calls resync_native() to catch up."""
    try:
        from .. import _native
        if not _native.is_loaded():
            return
        code = {bool: _native.FLAG_BOOL, int: _native.FLAG_INT,
                float: _native.FLAG_DOUBLE}.get(ty, _native.FLAG_STRING)
        # define (idempotent; applies env default) then set the explicit
        # current value so set_flags wins over a stale FLAGS_* env override.
        if code == _native.FLAG_STRING:
            _native.flag_define(name, code, str(value), 0.0, help_)
            _native.flag_set(name, str(value))
        else:
            _native.flag_define(name, code, "", float(value), help_)
            _native.flag_set(name, float(value))
    except Exception:
        pass


def resync_native():
    """Push the whole Python registry into the native one (called by
    _native.load() after the library comes up)."""
    for f in _REGISTRY.values():
        _native_mirror(f.name, f.type, f.value, f.help)


def define_flag(name: str, default, help: str = "", type_: type | None = None,
                on_change=None):
    ty = type_ or type(default)
    env = os.environ.get(f"FLAGS_{name}")
    value = _coerce(ty, env) if env is not None else default
    flag = _Flag(name=name, default=default, type=ty, help=help,
                 on_change=on_change, value=value)
    _REGISTRY[name] = flag
    _native_mirror(name, ty, value, help)
    if on_change is not None and env is not None:
        on_change(value)
    return flag


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags"""
    for k, v in flags.items():
        k = k.removeprefix("FLAGS_")
        if k not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        f = _REGISTRY[k]
        f.value = _coerce(f.type, v)
        _native_mirror(k, f.type, f.value, f.help)
        if f.on_change is not None:
            f.on_change(f.value)


def get_flags(flags) -> Dict[str, Any]:
    """paddle.get_flags"""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        k2 = k.removeprefix("FLAGS_")
        if k2 not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        out[k] = _REGISTRY[k2].value
    return out


def flag_value(name: str):
    return _REGISTRY[name].value


def all_flags():
    return {k: f.value for k, f in _REGISTRY.items()}


def _set_debug_nans(v: bool):
    import jax
    jax.config.update("jax_debug_nans", bool(v))


# Core flags (parity names with paddle/phi/core/flags.cc where meaningful).
define_flag("check_nan_inf", False,
            "Scan op outputs for NaN/Inf (maps to jax_debug_nans).",
            on_change=_set_debug_nans)
define_flag("check_nan_inf_level", 0, "NaN check verbosity level.")
define_flag("allocator_strategy", "auto_growth",
            "Parity stub: XLA/TPU memory is arena-managed by the runtime.")
define_flag("cudnn_deterministic", False,
            "Deterministic kernels (TPU: XLA is deterministic by default).")
define_flag("use_pallas_kernels", True,
            "Use Pallas fused kernels (attention/LN/RoPE) when on TPU.")
define_flag("pallas_interpret", False,
            "Force Pallas kernels ON in interpreter mode (CPU CI coverage: "
            "runs every kernel's real Pallas path without TPU hardware).")
define_flag("flash_block_q", 128,
            "Flash-attention Q tile rows (on-device autotune knob).")
define_flag("flash_block_k", 128,
            "Flash-attention KV tile rows (on-device autotune knob).")
define_flag("host_init", False,
            "Sample parameter initializers on the host (numpy) instead of "
            "via device jax.random ops. Same statistical distributions and "
            "seed-determinism, different random stream. On a tunnelled/"
            "remote-compile TPU this removes every per-parameter "
            "compile+execute roundtrip from model construction.")
define_flag("max_inplace_grad_add", 0, "Parity stub.")
define_flag("eager_delete_tensor_gb", 0.0, "Parity stub; XLA GC is automatic.")
define_flag("shm_channel_capacity_mb", 64,
            "Per-DataLoader shared-memory ring capacity (native worker pool).")
define_flag("obs_xla_mfu", False,
            "Telemetry MFU numerator from XLA's cost model (one extra "
            "lowering per batch signature) instead of the 6*N analytic "
            "estimate.")
define_flag("fused_optimizer", True,
            "Fused multi-tensor optimizer path: eager Optimizer.step() "
            "flattens (param, grad, accumulator) leaves into dtype-"
            "bucketed flat buffers and updates them in ONE jitted, "
            "donated program (O(#dtype buckets) dispatches instead of "
            "O(#params)). Per-param math is the fallback for non-fusible "
            "configs (custom regularizer callables, Lamb, ...).")
define_flag("quantized_grad_comm", False,
            "int8 gradient collectives with per-bucket scales and an "
            "error-feedback residual (EQuARX-style, arXiv:2506.17615). "
            "Applies to collective.quantized_* and, when "
            "weight_update_sharding is on, to DistTrainStep's gradient "
            "reduction. ~4x comm-byte reduction; adds quantization "
            "noise bounded by the error-feedback loop.")
define_flag("grad_bucket_bytes", 32 * 1024 * 1024,
            "Target flat-bucket payload size for gradient collectives "
            "(collective.GradBucketer). Smaller buckets let XLA overlap "
            "communication with the optimizer update; larger buckets "
            "amortize per-collective latency.")
define_flag("check_distribution_args", False,
            "Validate distribution constructor arguments (e.g. negative "
            "Categorical weights) with a warning. Costs a host sync on "
            "device-resident weights, so it is debug-only.")


def _arm_faults(v):
    from . import faults
    faults.arm(v)


define_flag("fault_injection", "",
            "Deterministic fault-injection spec (docs/ROBUSTNESS.md): "
            "comma-separated 'site[:key=val|mode]...' entries, e.g. "
            "'ckpt_save:step=3:err,nan_loss:step=5'. Empty disarms. "
            "Sites: ckpt_save, ckpt_write, ckpt_slow, nan_loss, "
            "slow_step, rank_hang, sigterm, decode_wedge, serve_flood, "
            "collective_stall, heartbeat_stall.",
            on_change=_arm_faults)
define_flag("anomaly_guard", True,
            "Trainer anomaly guard: a NaN/Inf loss skips the parameter "
            "update IN-PROGRAM (params/opt-state/buffers keep their "
            "pre-step values — a handful of fused selects, no host "
            "sync), the anomalous step is never checkpointed, and the "
            "loop aborts after FLAGS_max_anomalous_steps consecutive "
            "bad steps. The Trainer syncs the loss one step late "
            "(pipelined) to count anomalies; 0 restores the unguarded "
            "log-boundary-only sync behavior.")
define_flag("max_anomalous_steps", 10,
            "Abort training with AnomalousTrainingError after this many "
            "CONSECUTIVE anomalous (NaN/Inf or loss-spike) steps.")
define_flag("loss_spike_factor", 10.0,
            "Loss-spike anomaly threshold: a step whose loss exceeds "
            "this multiple of the rolling mean of recent good losses "
            "counts as anomalous (not checkpointed; counts toward the "
            "abort threshold). 0 disables spike detection; NaN/Inf "
            "detection is always on while FLAGS_anomaly_guard is set.")
define_flag("ckpt_save_retries", 3,
            "VerifiedCheckpointer: retries after a failed checkpoint "
            "save (transient I/O error), with jittered exponential "
            "backoff, before the error propagates.")
define_flag("ckpt_retry_backoff_s", 0.5,
            "Base delay (seconds) for checkpoint save retry backoff; "
            "doubles per attempt (capped at 8s), +/-50% jitter.")
define_flag("serve_prefill_chunk_tokens", 0,
            "ContinuousBatchingPredictor chunked prefill: prompts "
            "longer than this many tokens are ingested as page-aligned "
            "chunks interleaved with decode ticks (one mixed "
            "prefill+decode program per tick) instead of one "
            "monolithic prefill that stalls every in-flight decode. "
            "Rounded DOWN to a power-of-two multiple of page_size (a "
            "latency bound; min one page); the per-tick chunk shrinks "
            "under decode load. 0 disables (constructor "
            "prefill_chunk_tokens overrides).")
define_flag("serve_spec_draft_tokens", 0,
            "Speculative decoding: up to this many prompt-lookup "
            "drafted tokens are verified per compiled decode step "
            "(the verify span is draft_tokens + 1 wide; greedy output "
            "is bitwise-identical to plain greedy decode, sampled "
            "output rejection-sampling-correct). 0 disables "
            "(constructor spec_draft_tokens overrides; "
            "docs/SERVING.md 'Speculative decoding & sampling').")
define_flag("serve_spec_ngram_max", 3,
            "Prompt-lookup drafting: longest suffix n-gram matched "
            "against the request's own prompt+generation history when "
            "proposing draft tokens (host-side, no second model).")
define_flag("serve_sampling", False,
            "Serve-loop on-device sampling: compile the decode step "
            "with per-request temperature/top-k/top-p/seed as batched "
            "operands (requests without SamplingParams stay greedy — "
            "temperature 0 reduces to the argmax bitwise). Off keeps "
            "the plain argmax decode program.")
define_flag("serve_tp_degree", 1,
            "Tensor-parallel serving degree: each "
            "ContinuousBatchingPredictor replica spans this many "
            "devices — weights are NamedSharding'ed over the 'model' "
            "mesh axis and PagedKVPool pages are sharded over KV "
            "heads, so every serve program runs GSPMD-partitioned. "
            "Compiled-in geometry: joins the AOT bundle topology "
            "fingerprint (a mismatch invalidates with reason "
            "'topology'). 1 = single-device replicas (constructor "
            "tp_degree overrides; docs/SERVING.md 'Tensor-parallel "
            "replicas').")
define_flag("serve_role", "unified",
            "Disaggregated serving role of this replica: 'unified' "
            "(prefill+decode on one device group, the historical "
            "default), 'prefill' (fills KV pages and hands off at "
            "first token), or 'decode' (resumes the sync-free loop "
            "from an imported KV page span). Joins the AOT bundle "
            "fingerprint next to topology (mismatch invalidates with "
            "reason 'role'); per-role RuntimeConfig overlays apply via "
            "RuntimeConfig.for_role (docs/SERVING.md 'Disaggregated "
            "prefill/decode').")
define_flag("serve_decode_watchdog_s", 0.0,
            "ContinuousBatchingPredictor decode watchdog: if a decode "
            "step's host sync does not resolve within this many "
            "seconds, pending requests fail with last_status "
            "'watchdog' instead of generate() hanging. 0 disables "
            "(the resolve blocks unconditionally, no polling).")
define_flag("collective_timeout_s", 0.0,
            "Collective deadline: if a collective's host-side sync "
            "(distributed.wait / barrier) does not resolve within this "
            "many seconds, raise CollectiveTimeoutError (with a flight "
            "dump) instead of hanging forever on a peer that never "
            "reached the collective. 0 disables (block "
            "unconditionally).")
define_flag("ckpt_async_save", True,
            "Trainer checkpointing drains in the background: save() "
            "takes only the device->host snapshot at the step boundary "
            "and a drain thread runs the write/digest/manifest/rename "
            "pipeline (all atomicity/verification/retry guarantees "
            "kept; wait() blocks on the drain). Off restores the "
            "fully synchronous save.")
define_flag("ckpt_drain_deadline_s", 30.0,
            "Preemption drain deadline: on SIGTERM/SIGINT the Trainer "
            "blocks at most this many seconds for in-flight background "
            "checkpoint drains before exiting (a drain that misses the "
            "deadline counts robustness.ckpt_drain_timeouts and keeps "
            "draining on its daemon thread). <=0 waits forever.")
